"""MoE model: routing invariants, causality, training, and hybrid
gossip-DP x expert-parallel execution (the EP analogue of the TP test —
reference has no MoE, SURVEY.md §2; this extends the parallelism matrix).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from consensusml_tpu.comm import WorkerMesh
from consensusml_tpu.consensus import GossipConfig
from consensusml_tpu.models.moe import (
    MoEConfig,
    MoELM,
    moe_loss_fn,
    moe_tiny,
    top_k_routing,
)
from consensusml_tpu.parallel import moe_ep_rules
from consensusml_tpu.topology import RingTopology
from consensusml_tpu.train import (
    LocalSGDConfig,
    init_stacked_state,
    make_collective_train_step,
    make_simulated_train_step,
)

VOCAB = 64


def _lm_batches(world, h, batch, seq, rounds, seed=0):
    rng = np.random.default_rng(seed)
    for _ in range(rounds):
        start = rng.integers(0, VOCAB, size=(world, h, batch, 1))
        ids = (start + np.arange(seq)) % VOCAB
        yield {"input_ids": jnp.asarray(ids, jnp.int32)}


# ---------------------------------------------------------------------------
# routing math
# ---------------------------------------------------------------------------


def test_routing_respects_capacity_and_topk():
    rng = np.random.default_rng(0)
    probs = jax.nn.softmax(jnp.asarray(rng.normal(size=(2, 16, 4)) * 3), axis=-1)
    k, cap = 2, 5
    dispatch, combine = jax.jit(top_k_routing, static_argnums=(1, 2))(probs, k, cap)
    d = np.asarray(dispatch)
    # each (expert, slot) holds at most one token per batch row
    assert d.sum(axis=1).max() <= 1.0 + 1e-6
    # each token lands in at most k slots total, each expert at most once
    assert d.sum(axis=(2, 3)).max() <= k + 1e-6
    assert d.sum(axis=3).max() <= 1.0 + 1e-6
    # per expert, per row: at most `cap` tokens
    assert d.sum(axis=(1, 3)).max() <= cap + 1e-6
    # combine weights live on dispatched slots only and sum to <= 1 per token
    c = np.asarray(combine)
    assert ((c > 0) <= (d > 0)).all()
    assert c.sum(axis=(2, 3)).max() <= 1.0 + 1e-5


def test_routing_no_drop_when_capacity_ample():
    """With capacity >= S every token keeps all k routes, gates sum to 1."""
    rng = np.random.default_rng(1)
    probs = jax.nn.softmax(jnp.asarray(rng.normal(size=(1, 8, 4))), axis=-1)
    dispatch, combine = top_k_routing(probs, 2, 8)
    np.testing.assert_allclose(np.asarray(dispatch).sum(axis=(2, 3)), 2.0, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(combine).sum(axis=(2, 3)), 1.0, rtol=1e-4)


def test_routing_slot_major_priority():
    """A token's FIRST choice beats another token's second choice: with
    capacity 1, expert e's single slot goes to the token that ranked e
    first, even if an earlier-in-sequence token ranked it second."""
    # token 0: expert 1 first, expert 0 second. token 1: expert 0 first.
    probs = jnp.asarray([[[0.4, 0.6], [0.9, 0.1]]])  # (1, 2, 2)
    dispatch, _ = top_k_routing(probs, 2, 1)
    d = np.asarray(dispatch)[0]  # (S=2, E=2, C=1)
    assert d[1, 0, 0] == 1.0  # token 1 won expert 0 (its first choice)
    assert d[0, 0, 0] == 0.0  # token 0's second choice lost
    assert d[0, 1, 0] == 1.0  # token 0 kept its first choice


# ---------------------------------------------------------------------------
# model
# ---------------------------------------------------------------------------


def test_moe_forward_shapes_and_aux():
    model = moe_tiny(vocab_size=VOCAB)
    ids = jnp.zeros((2, 16), jnp.int32)
    variables = model.init(jax.random.key(0), ids)
    logits, aux = model.apply(variables, ids)
    assert logits.shape == (2, 16, VOCAB) and logits.dtype == jnp.float32
    # balanced-ish at init; hard imbalance would push aux toward n_experts
    assert 0.9 <= float(aux) <= 3.0
    # expert weights carry the stacked (E, d, f) layout EP shards
    wi = variables["params"]["layer_0"]["moe"]["wi"]
    assert wi.shape == (4, 32, 64)


def test_moe_causality():
    model = moe_tiny(vocab_size=VOCAB)
    ids = jnp.zeros((1, 16), jnp.int32)
    variables = model.init(jax.random.key(0), ids)
    a, _ = model.apply(variables, ids)
    b, _ = model.apply(variables, ids.at[0, 10].set(5))
    np.testing.assert_allclose(a[0, :10], b[0, :10], atol=1e-4)
    assert not np.allclose(a[0, 10:], b[0, 10:], atol=1e-4)


def test_moe_interleave():
    """moe_every=2 alternates dense and MoE blocks."""
    model = MoELM(
        config=MoEConfig(
            vocab_size=VOCAB, hidden=32, layers=4, heads=2, mlp_dim=64,
            n_experts=2, moe_every=2, max_len=32,
        )
    )
    params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    assert "moe" not in params["layer_0"] and "moe" in params["layer_1"]
    assert "moe" not in params["layer_2"] and "moe" in params["layer_3"]


def test_moe_local_sgd_trains():
    """Gossip local-SGD on the MoE model: loss decreases, experts used."""
    topo = RingTopology(4)
    model = moe_tiny(vocab_size=VOCAB)
    cfg = LocalSGDConfig(
        gossip=GossipConfig(topology=topo), optimizer=optax.adam(3e-3), h=2
    )
    step = make_simulated_train_step(cfg, moe_loss_fn(model))
    init = lambda r: model.init(r, jnp.zeros((1, 16), jnp.int32))["params"]
    state = init_stacked_state(cfg, init, jax.random.key(0), 4)
    losses = []
    for batch in _lm_batches(4, h=2, batch=8, seq=16, rounds=25, seed=2):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < 0.7 * losses[0], f"{losses[0]} -> {losses[-1]}"


# ---------------------------------------------------------------------------
# hybrid gossip-DP x EP
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("ep", [2, 4])
def test_moe_ep_matches_simulated(ep):
    """Ring-gossip workers x ep-submesh == simulated mixing-matrix oracle."""
    world = 8 // ep
    model = moe_tiny(vocab_size=VOCAB, dtype=jnp.float32)
    cfg = LocalSGDConfig(
        gossip=GossipConfig(topology=RingTopology(world)),
        optimizer=optax.sgd(0.05, momentum=0.9),
        h=2,
    )
    loss_fn = moe_loss_fn(model)
    init = lambda r: model.init(r, jnp.zeros((1, 16), jnp.int32))["params"]

    wmesh = WorkerMesh.create(
        cfg.gossip.topology, devices=jax.devices()[:8], model_axes=(("ep", ep),)
    )
    state_c = init_stacked_state(cfg, init, jax.random.key(0), world)
    state_c = wmesh.shard_stacked(state_c, rules=moe_ep_rules("ep"))
    wi = state_c.params["layer_0"]["moe"]["wi"]
    assert wi.sharding.spec[1] == "ep", f"expected ep-sharded wi, got {wi.sharding}"

    step_c = make_collective_train_step(cfg, loss_fn, wmesh)
    step_s = make_simulated_train_step(cfg, loss_fn)
    state_s = init_stacked_state(cfg, init, jax.random.key(0), world)

    for batch in _lm_batches(world, h=2, batch=4, seq=16, rounds=2, seed=0):
        batch_c = wmesh.shard_stacked(batch)
        state_c, m_c = step_c(state_c, batch_c)
        state_s, m_s = step_s(state_s, batch)

    np.testing.assert_allclose(
        float(m_c["loss"]), float(m_s["loss"]), rtol=1e-3, atol=1e-4
    )
    for a, b in zip(jax.tree.leaves(state_c.params), jax.tree.leaves(state_s.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-4)
