"""Watchdog timeout path + flight-recorder dump (previously untested:
the only exit was ``os._exit``, unreachable in-process — the injectable
``exit_fn``/``on_timeout`` hooks exist exactly so this file can cover
the stall behavior without killing pytest)."""

import json
import os
import time

import pytest

from consensusml_tpu.obs import FlightRecorder, MetricsRegistry, SpanTracer
from consensusml_tpu.utils import ProgressWatchdog

pytestmark = pytest.mark.telemetry


def _wait_for(pred, timeout_s=10.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        if pred():
            return True
        time.sleep(0.02)
    return False


def test_stalled_round_dumps_flight_recorder_and_exits(tmp_path):
    tracer = SpanTracer()
    registry = MetricsRegistry()
    # a few rounds of evidence the dump must carry
    for rnd in range(3):
        with tracer.span("train.round", round=rnd):
            pass
        registry.counter("consensusml_rounds_total").inc()
        registry.snapshot({"round": rnd})
    recorder = FlightRecorder(
        str(tmp_path / "fr"), tracer=tracer, registry=registry
    )
    exits = []
    wd = ProgressWatchdog(
        timeout_s=0.2,
        label="test round",
        on_timeout=recorder.dump,
        exit_fn=exits.append,
    ).start()
    try:
        wd.beat("round 2")  # arm, then stall: no further beats
        assert _wait_for(lambda: exits)
    finally:
        wd.stop()
    assert exits == [3]  # the distinct peer-loss exit code

    # the flight-recorder file exists and parses (the acceptance check)
    files = os.listdir(tmp_path / "fr")
    assert len(files) == 1 and files[0].startswith("flightrec-")
    doc = json.load(open(tmp_path / "fr" / files[0]))
    assert doc["reason"].startswith("watchdog-timeout")
    assert "test round" in doc["reason"]
    assert [s["args"]["round"] for s in doc["spans"]] == [0, 1, 2]
    assert [s["round"] for s in doc["metric_snapshots"]] == [0, 1, 2]
    assert (
        doc["metrics_final"]["metrics"]["consensusml_rounds_total"] == 3
    )


def test_beating_watchdog_never_dumps_or_exits(tmp_path):
    recorder = FlightRecorder(
        str(tmp_path / "fr"), tracer=SpanTracer(), registry=MetricsRegistry()
    )
    exits = []
    wd = ProgressWatchdog(
        timeout_s=0.5,
        on_timeout=recorder.dump,
        exit_fn=exits.append,
    ).start()
    try:
        for _ in range(8):
            wd.beat("ok")
            time.sleep(0.1)
    finally:
        wd.stop()
    time.sleep(0.2)
    assert exits == []
    assert not os.path.exists(tmp_path / "fr")


def test_failing_on_timeout_hook_still_exits():
    exits = []

    def bad_hook(reason):
        raise RuntimeError("dump target vanished")

    wd = ProgressWatchdog(
        timeout_s=0.2, on_timeout=bad_hook, exit_fn=exits.append
    ).start()
    try:
        wd.beat("armed")
        assert _wait_for(lambda: exits)
    finally:
        wd.stop()
    assert exits == [3]
