"""Live-membership subsystem (consensusml_tpu.swarm; docs/elasticity.md).

Pins the acceptance scenario end to end: a seeded churn schedule with
3 joins + 2 drops + 1 straggler over 12 simulated rounds runs to
completion with NO checkpoint read on join, the gossip-bootstrapped
joiners land within epsilon of the swarm consensus mean, and the
post-churn loss stays within tolerance of the churn-free run at equal
data — plus the membership controller's barrier-free epoch protocol,
schedule determinism/round-tripping, push-sum-as-default resolution,
and the per-rank labeled fault counters.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from consensusml_tpu.comm import simulated
from consensusml_tpu.consensus import FaultConfig, GossipConfig
from consensusml_tpu.data import SyntheticClassification, round_batches
from consensusml_tpu.models import MLP, mlp_loss_fn
from consensusml_tpu.swarm import (
    ChurnEvent,
    ChurnSchedule,
    MembershipController,
    bootstrap_rounds_for,
    churn_config,
    gossip_bootstrap,
    run_churn,
)
from consensusml_tpu.topology import (
    OnePeerExponentialTopology,
    RingTopology,
    TorusTopology,
    rederive,
)
from consensusml_tpu.train import LocalSGDConfig
from consensusml_tpu.utils.tree import consensus_mean

pytestmark = pytest.mark.swarm


# ---------------------------------------------------------------------------
# churn schedules
# ---------------------------------------------------------------------------


def test_schedule_generate_is_deterministic_and_roundtrips():
    kw = dict(seed=7, rounds=12, joins=3, drops=2, stragglers=1, initial_world=4)
    s1 = ChurnSchedule.generate(**kw)
    s2 = ChurnSchedule.generate(**kw)
    assert s1 == s2
    assert ChurnSchedule.parse(s1.spec()) == s1
    assert s1.counts()["join"] == 3 and s1.counts()["drop"] == 2
    assert s1.counts()["straggle"] == 1
    # a different seed is a different schedule
    assert ChurnSchedule.generate(**{**kw, "seed": 8}) != s1
    # generator-form spec parses too
    s3 = ChurnSchedule.parse(
        "seed=7,rounds=12,joins=3,drops=2,stragglers=1,initial_world=4"
    )
    assert s3 == s1


def test_schedule_parse_explicit_and_errors():
    s = ChurnSchedule.parse("join@2:2;drop@4:1,3;straggle@5:0x3;rejoin@6:1")
    assert s.total_joins == 2
    assert s.events_at(4)[0].workers == (1, 3)
    assert s.events_at(5)[0].duration == 3
    with pytest.raises(ValueError, match="kind"):
        ChurnSchedule.parse("explode@3:1")
    with pytest.raises(ValueError, match="empty"):
        ChurnSchedule.parse(" ; ")
    with pytest.raises(ValueError, match="slots"):
        ChurnSchedule.parse("drop@3")
    with pytest.raises(ValueError, match="droppable"):
        ChurnSchedule.generate(seed=0, rounds=20, drops=5, initial_world=3)


# ---------------------------------------------------------------------------
# membership controller: epoch views + barrier-free transitions
# ---------------------------------------------------------------------------


def test_controller_barrier_free_transition():
    ctl = MembershipController(RingTopology(4))
    v0 = ctl.pin()  # an in-flight round holds epoch 0
    assert v0.epoch == 0 and v0.world_size == 4

    ctl.propose_join(2)
    v1 = ctl.advance()  # next round's view installs WITHOUT a barrier
    assert v1.epoch == 1 and v1.world_size == 6
    assert ctl.view() is v1

    # the pinned old view is untouched: same members, same topology
    assert v0.world_size == 4 and v0.topology.world_size == 4
    assert ctl.pinned_epochs() == (0,)
    ctl.release(v0)
    assert ctl.pinned_epochs() == ()
    with pytest.raises(ValueError, match="not pinned"):
        ctl.release(v0)


def test_controller_rederives_topology_on_membership_change():
    ctl = MembershipController(RingTopology(4))
    ctl.propose_join(3)
    v = ctl.advance()
    assert v.topology.world_size == 7 and v.topology.name == "ring"
    # torus re-factors at the new size
    ctl2 = MembershipController(TorusTopology(2, 2))
    ctl2.propose_join(2)
    v2 = ctl2.advance()
    assert v2.topology.world_size == 6 and v2.topology.name == "torus"


def test_controller_status_flow_and_masks():
    ctl = MembershipController(RingTopology(4))
    ctl.propose_drop([1])
    ctl.propose_straggle([3], rounds=2)
    v = ctl.advance()
    np.testing.assert_array_equal(v.alive_mask(), [1, 0, 1, 0])
    np.testing.assert_array_equal(v.frozen_mask(), [0, 1, 0, 0])
    # straggle window ticks down on advance; drop stays until rejoin
    v = ctl.advance()
    np.testing.assert_array_equal(v.alive_mask(), [1, 0, 1, 0])
    v = ctl.advance()
    np.testing.assert_array_equal(v.alive_mask(), [1, 0, 1, 1])
    ctl.propose_rejoin([1])
    v = ctl.advance()
    np.testing.assert_array_equal(v.alive_mask(), [1, 1, 1, 1])
    with pytest.raises(ValueError, match="not dead"):
        ctl.propose_rejoin([0])
        ctl.advance()


def test_controller_refuses_empty_swarm():
    ctl = MembershipController(RingTopology(2))
    ctl.propose_drop([0, 1])
    with pytest.raises(ValueError, match="no active member"):
        ctl.advance()


# ---------------------------------------------------------------------------
# gossip bootstrap: within epsilon of the consensus mean, no checkpoint
# ---------------------------------------------------------------------------


def test_gossip_bootstrap_within_epsilon_of_consensus_mean():
    rng = np.random.default_rng(0)
    tree = {
        "w": jnp.asarray(rng.normal(size=(6, 4, 3)), jnp.float32),
        "b": jnp.asarray(rng.normal(size=(6, 3)), jnp.float32),
    }
    tol = 1e-3
    topo = rederive(RingTopology(6), 8)
    rows, info = gossip_bootstrap(tree, topo, 2, tol=tol)
    mean = consensus_mean(tree)
    # the reported epsilon is measured against the SHARED consensus-mean
    # definition and honors the requested tolerance
    assert info["eps_measured"] <= tol
    ref = np.sqrt(
        sum(float((np.asarray(m, np.float64) ** 2).sum()) for m in jax.tree.leaves(mean))
    )
    for j in range(2):
        err = np.sqrt(
            sum(
                float(((np.asarray(r, np.float64)[j] - np.asarray(m, np.float64)) ** 2).sum())
                for r, m in zip(jax.tree.leaves(rows), jax.tree.leaves(mean))
            )
        )
        assert err / ref <= tol
    # the spectral-gap estimate sizes the first burst; the adaptive loop
    # may extend past it (the enforcement half of the guarantee)
    assert info["rounds"] >= bootstrap_rounds_for(topo, tol=tol)


def test_gossip_bootstrap_explicit_rounds_runs_exactly():
    rng = np.random.default_rng(2)
    tree = {"p": jnp.asarray(rng.normal(size=(4, 5)), jnp.float32)}
    _, info = gossip_bootstrap(tree, rederive(RingTopology(4), 5), 1, rounds=20)
    assert info["rounds"] == 20
    # dense contracts in one round but still honors the explicit count
    from consensusml_tpu.topology import DenseTopology

    _, info = gossip_bootstrap(tree, DenseTopology(5), 1, rounds=7)
    assert info["rounds"] == 7


def test_validate_schedule_rejects_bad_sequences_before_training():
    from consensusml_tpu.swarm import validate_schedule

    topo = RingTopology(4)
    with pytest.raises(ValueError, match="round 2.*not dead"):
        validate_schedule(ChurnSchedule.parse("rejoin@2:1"), topo, 6)
    with pytest.raises(ValueError, match="dead member"):
        validate_schedule(
            ChurnSchedule.parse("drop@1:2;straggle@3:2x2"), topo, 6
        )
    with pytest.raises(ValueError, match="beyond"):
        validate_schedule(ChurnSchedule.parse("drop@9:1"), topo, 6)
    with pytest.raises(ValueError, match="capacity"):
        validate_schedule(ChurnSchedule.parse("join@1:1;drop@2:7"), topo, 6)
    # a valid sequence reports the reached capacity
    assert validate_schedule(
        ChurnSchedule.parse("join@1:2;drop@2:1;rejoin@4:1"), topo, 6
    ) == 6
    # and run_churn fails fast (before any round) on the same input
    model = MLP(hidden=8)
    cfg = LocalSGDConfig(
        gossip=GossipConfig(topology=topo), optimizer=optax.sgd(0.1), h=1
    )
    data = SyntheticClassification(n=64, image_shape=(8, 8, 1))
    with pytest.raises(ValueError, match="round 2"):
        run_churn(
            cfg, mlp_loss_fn(model),
            lambda r: model.init(r, jnp.zeros((1, 8, 8, 1)))["params"],
            ChurnSchedule.parse("rejoin@2:1"), rounds=6,
            batches=lambda n, s: round_batches(data, 4, 1, 8, n, seed=s),
        )


def test_validate_schedule_matches_live_staging_order():
    """A straggle/drop of a slot that only joins the SAME round must be
    rejected up front — validate stages in run_churn's exact order
    (non-joins mid-round, joins at the boundary)."""
    from consensusml_tpu.swarm import validate_schedule

    topo = RingTopology(4)
    with pytest.raises(ValueError, match="round 3.*out of range"):
        validate_schedule(
            ChurnSchedule.parse("join@3:1;straggle@3:4x2"), topo, 6
        )
    with pytest.raises(ValueError, match="round 3.*out of range"):
        validate_schedule(ChurnSchedule.parse("join@3:1;drop@3:4"), topo, 6)
    # the slot is usable from the NEXT round
    assert validate_schedule(
        ChurnSchedule.parse("join@3:1;drop@4:4"), topo, 6
    ) == 5


def test_gossip_bootstrap_warns_when_cap_truncates_below_tol():
    import warnings

    rng = np.random.default_rng(6)
    # ring(24) mixes far too slowly for tol=1e-9 inside the 64-round cap
    tree = {"p": jnp.asarray(rng.normal(size=(24, 4)), jnp.float32)}
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        _, info = gossip_bootstrap(
            tree, rederive(RingTopology(24), 25), 1, tol=1e-9
        )
    assert not info["converged"]
    assert info["rounds"] == 64
    assert any("OUTSIDE the" in str(w.message) for w in caught)
    with pytest.raises(ValueError, match=">= 1"):
        gossip_bootstrap(tree, rederive(RingTopology(24), 25), 1, rounds=0)


def test_analysis_consumers_use_resolved_push_sum():
    """push_sum='auto' resolving to DISABLED must not trip the push-sum
    branches of the schedule verifier (pre-existing truthiness checks)."""
    from consensusml_tpu.analysis.schedule import materialize_schedules
    from consensusml_tpu.consensus import ConsensusEngine

    eng = ConsensusEngine(
        GossipConfig(topology=RingTopology(4), push_sum="auto")
    )
    assert not eng.config.push_sum_enabled
    # must NOT raise NotImplementedError("push-sum rounds...")
    scheds = materialize_schedules(eng, [((8,), jnp.float32)])
    assert len(scheds) == 4


def test_gossip_bootstrap_leaves_survivors_untouched():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(4, 5)), jnp.float32)
    before = np.asarray(x).copy()
    gossip_bootstrap({"p": x}, rederive(RingTopology(4), 5), 1)
    np.testing.assert_array_equal(np.asarray(x), before)


# ---------------------------------------------------------------------------
# push-sum-weighted recovery as the default under asymmetric membership
# ---------------------------------------------------------------------------


def test_push_sum_auto_resolution():
    directed = OnePeerExponentialTopology(8)
    ring = RingTopology(8)
    # asymmetric + faults => push-sum engages
    g = GossipConfig(
        topology=directed, faults=FaultConfig(0.1), push_sum="auto"
    )
    assert g.push_sum_enabled
    # symmetric graphs keep the receive-side fold (coincides w/ push-sum)
    assert not GossipConfig(
        topology=ring, faults=FaultConfig(0.1), push_sum="auto"
    ).push_sum_enabled
    # no fault model => nothing to recover from
    assert not GossipConfig(topology=directed, push_sum="auto").push_sum_enabled
    # the engine actually runs the push-sum path: state carries mass
    from consensusml_tpu.consensus import ConsensusEngine, PushSumState

    st = ConsensusEngine(g).init_state({"p": jnp.zeros((8, 3))}, world_size=8)
    assert isinstance(st, PushSumState)
    with pytest.raises(ValueError, match="push_sum"):
        GossipConfig(topology=ring, push_sum="sometimes")


def test_churn_config_defaults():
    cfg = LocalSGDConfig(
        gossip=GossipConfig(topology=OnePeerExponentialTopology(4)),
        optimizer=optax.sgd(0.1),
    )
    out = churn_config(cfg)
    assert out.gossip.faults is not None
    assert out.gossip.push_sum == "auto" and out.gossip.push_sum_enabled
    from consensusml_tpu.compress import topk_int8_compressor

    comp_cfg = LocalSGDConfig(
        gossip=GossipConfig(
            topology=RingTopology(4),
            compressor=topk_int8_compressor(ratio=0.5, chunk=128),
            gamma=0.5,
        ),
        optimizer=optax.sgd(0.1),
    )
    with pytest.raises(NotImplementedError, match="compressed"):
        churn_config(comp_cfg)


# ---------------------------------------------------------------------------
# per-rank labeled fault counters (metrics registry label support)
# ---------------------------------------------------------------------------


def test_record_fault_metrics_per_rank_labels(monkeypatch):
    from consensusml_tpu import obs
    from consensusml_tpu.consensus import record_fault_metrics
    from consensusml_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()
    monkeypatch.setattr(obs, "get_registry", lambda: reg)
    record_fault_metrics(0.75, alive=[1, 0, 1, 1])
    record_fault_metrics(0.75, alive=[1, 0, 0, 1], prev_alive=[1, 0, 1, 1])
    record_fault_metrics(1.0, alive=[1, 1, 1, 1], prev_alive=[1, 0, 0, 1])
    vals = {m.key: m.value_dict() for m in reg.metrics()}
    assert vals['consensusml_worker_drop_rounds_total{worker="1"}'] == 2
    assert vals['consensusml_worker_drop_rounds_total{worker="2"}'] == 1
    assert vals['consensusml_worker_recoveries_total{worker="1"}'] == 1
    assert vals['consensusml_worker_recoveries_total{worker="2"}'] == 1
    assert 'consensusml_worker_drop_rounds_total{worker="0"}' not in vals


# ---------------------------------------------------------------------------
# the tier-1 churn smoke: the acceptance scenario end to end
# ---------------------------------------------------------------------------

SMOKE_ROUNDS = 12
SMOKE_INITIAL = 4


@pytest.fixture(scope="module")
def churn_runs():
    """One churn replay + its equal-data churn-free reference.

    Deliberately in the FAST tier despite the compile cost: this is the
    acceptance-critical scenario (3 joins + 2 drops + 1 straggler over
    12 simulated rounds, loss continuity pinned in tier-1)."""
    schedule = ChurnSchedule.generate(
        seed=0, rounds=SMOKE_ROUNDS, joins=3, drops=2, stragglers=1,
        initial_world=SMOKE_INITIAL,
    )
    capacity = SMOKE_INITIAL + schedule.total_joins
    model = MLP(hidden=8)
    cfg = LocalSGDConfig(
        gossip=GossipConfig(topology=RingTopology(SMOKE_INITIAL)),
        optimizer=optax.sgd(0.1),
        h=1,
    )
    data = SyntheticClassification(n=512, image_shape=(8, 8, 1))
    init = lambda r: model.init(r, jnp.zeros((1, 8, 8, 1)))["params"]
    batches = lambda n, s: round_batches(data, capacity, 1, 16, n, seed=s)
    churn = run_churn(
        cfg, mlp_loss_fn(model), init, schedule,
        rounds=SMOKE_ROUNDS, batches=batches, seed=0,
    )
    flat_cfg = dataclasses.replace(
        cfg,
        gossip=dataclasses.replace(
            cfg.gossip, topology=rederive(cfg.gossip.topology, capacity)
        ),
    )
    flat = run_churn(
        flat_cfg, mlp_loss_fn(model), init, ChurnSchedule(events=()),
        rounds=SMOKE_ROUNDS, batches=batches, seed=0,
    )
    return schedule, churn, flat


def test_churn_smoke_runs_to_completion(churn_runs):
    schedule, churn, _ = churn_runs
    assert len(churn.losses) == SMOKE_ROUNDS
    assert all(np.isfinite(l) for l in churn.losses)
    assert all(np.isfinite(e) for e in churn.consensus_errors)
    # every scheduled event made the timeline
    kinds = [e["kind"] for e in churn.events]
    assert kinds.count("join") == 3
    assert kinds.count("drop") == 2
    assert kinds.count("straggle") == 1
    # world grew by the joins; final membership is fully active (drops
    # rejoined per the generated schedule)
    assert churn.final_view.world_size == SMOKE_INITIAL + 3
    # epochs advanced once per event boundary (plus straggle recovery)
    assert churn.final_view.epoch >= len(churn.events)


def test_churn_joiners_bootstrap_from_gossip_not_checkpoints(churn_runs):
    _, churn, _ = churn_runs
    assert len(churn.bootstraps) == 3
    for b in churn.bootstraps:
        # the within-epsilon guarantee, measured against consensus_mean
        assert b["eps_measured"] <= b["tol"]
        assert b["rounds"] >= 1
    # the whole replay performed zero checkpoint I/O (nothing to read:
    # the harness takes no checkpoint path at all); the joins are step
    # rebuilds, not restarts — one per world size (initial + 3 1-joins)
    assert churn.recompiles == 4


def test_churn_loss_continuity_vs_no_churn_at_equal_data(churn_runs):
    _, churn, flat = churn_runs
    # both runs train on slot-identical streams; churn must not knock
    # the trajectory off course
    assert churn.losses[-1] < churn.losses[0]
    assert flat.losses[-1] < flat.losses[0]
    assert abs(churn.losses[-1] - flat.losses[-1]) < 0.5, (
        churn.losses, flat.losses,
    )


def test_churn_consensus_error_of_alive_members_stays_bounded(churn_runs):
    _, churn, _ = churn_runs
    # alive-member consensus error never explodes across churn (ring(7)
    # contracts every round; bootstrapped joiners start at the mean)
    assert max(churn.consensus_errors) < 10 * max(churn.consensus_errors[:2] + [1e-3])


def test_cluster_timeline_merges_and_renders(tmp_path, capsys):
    """Membership events recorded by the ClusterWriter surface in the
    aggregated report and in tools/obs_report.py's timeline rendering."""
    import importlib.util
    import os

    from consensusml_tpu.obs import ClusterWriter, aggregate
    from consensusml_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()
    reg.gauge("consensusml_swarm_epoch").set(3)
    reg.gauge("consensusml_swarm_members").set(5)
    reg.counter(
        "consensusml_swarm_events_total", labels={"kind": "join"}
    ).inc(2)
    w = ClusterWriter(str(tmp_path), rank=0, registry=reg, world_size=5)
    w.record_event(
        {
            "round": 2, "kind": "join", "workers": [4], "epoch": 1,
            "detail": {"bootstrap_rounds": 8, "eps_measured": 3e-4},
        }
    )
    w.record_event({"round": 5, "kind": "drop", "workers": [1], "epoch": 2})
    w.write(round=7)
    doc = aggregate(str(tmp_path))
    mem = doc["membership"]
    assert mem["epoch"] == 3 and mem["active_members"] == 5
    assert mem["event_counts"]["join"] == 2
    assert [r["kind"] for r in mem["timeline"]] == ["join", "drop"]

    spec = importlib.util.spec_from_file_location(
        "obs_report",
        os.path.join(
            os.path.dirname(__file__), "..", "tools", "obs_report.py"
        ),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "membership timeline" in out
    assert "bootstrap 8 rounds" in out
    assert "drop" in out and "w1" in out


@pytest.mark.slow
def test_cli_churn_schedule_end_to_end(tmp_path):
    """train.py --churn-schedule: the full CLI surface — schedule
    banner, live membership events with bootstrap epsilons, no
    checkpoint read, final swarm summary, obs timeline on disk."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    obs = str(tmp_path / "obs")
    r = subprocess.run(
        [
            sys.executable, os.path.join(repo, "train.py"),
            "--config", "mnist_mlp", "--device", "cpu",
            "--backend", "simulated", "--rounds", "10",
            "--churn-schedule", "join@2:1;drop@4:1;rejoin@6:1;straggle@7:2x2",
            "--obs-cluster-dir", obs, "--log-every", "5",
        ],
        capture_output=True, text=True, timeout=600, cwd=repo,
        env={**os.environ, "JAX_PLATFORMS": ""},
    )
    assert r.returncode == 0, r.stderr[-800:]
    assert "churn schedule:" in r.stdout
    assert "membership join: w4 (bootstrap" in r.stdout
    assert "membership drop: w1" in r.stdout
    assert "swarm final:" in r.stdout
    assert "1 gossip bootstraps (no checkpoint reads)" in r.stdout
    assert "final: loss=" in r.stdout
    from consensusml_tpu.obs import aggregate

    doc = aggregate(obs)
    kinds = [row["kind"] for row in doc["membership"]["timeline"]]
    assert kinds == ["join", "drop", "rejoin", "straggle"]
    # flag validation: collective backend is rejected loudly
    r2 = subprocess.run(
        [
            sys.executable, os.path.join(repo, "train.py"),
            "--config", "mnist_mlp", "--device", "cpu",
            "--backend", "collective", "--rounds", "4",
            "--churn-schedule", "join@2:1",
        ],
        capture_output=True, text=True, timeout=300, cwd=repo,
        env={**os.environ, "JAX_PLATFORMS": ""},
    )
    assert r2.returncode == 2
    assert "--churn-schedule" in r2.stderr


def test_consensus_error_masked_ignores_dead_rows():
    x = jnp.asarray(
        [[1.0, 1.0], [1.0, 1.0], [100.0, -100.0]], jnp.float32
    )
    full = simulated.consensus_error_stacked({"p": x}, 3)
    masked = simulated.consensus_error_masked({"p": x}, jnp.asarray([1.0, 1.0, 0.0]))
    assert float(masked) == pytest.approx(0.0, abs=1e-6)
    assert float(full) > 1.0
