"""int4 packed codec: jnp reference, Pallas kernels, and CHOCO use.

Wire format (Int4Payload): two's-complement nibbles in [-7, 7], byte j
of a chunk = element j (low) + element j+chunk//2 (high), scale =
absmax/7 per chunk — 8x wire compression for f32 (vs int8's 4x).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from consensusml_tpu.compress import (
    Int4Compressor,
    PallasInt4Compressor,
    topk_int4_compressor,
    topk_int8_compressor,
)
from consensusml_tpu.compress.kernels import dequantize_int4, quantize_int4


def test_roundtrip_error_bound():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(1000,)), jnp.float32)
    comp = Int4Compressor(chunk=128)
    p = comp.compress(x)
    assert p.data.dtype == jnp.uint8
    out = comp.decompress(p)
    err = np.abs(np.asarray(out) - np.asarray(x))
    bound = np.repeat(np.asarray(p.scales), 128)[: x.size] / 2 + 1e-7
    assert (err <= bound).all()


def test_exact_at_extremes_and_zeros():
    x = jnp.asarray([-3.5, 0.0, 3.5, 1.0])
    comp = Int4Compressor(chunk=4)
    out = comp.decompress(comp.compress(x))
    assert float(out[0]) == pytest.approx(-3.5)
    assert float(out[2]) == pytest.approx(3.5)
    z = comp.decompress(comp.compress(jnp.zeros(64)))
    np.testing.assert_array_equal(np.asarray(z), np.zeros(64))


def test_negative_nibbles_pack_and_unpack():
    """Every representable level survives the nibble pack exactly."""
    levels = jnp.asarray(np.arange(-7, 8), jnp.float32)  # 15 values
    comp = Int4Compressor(chunk=16)
    out = comp.decompress(comp.compress(levels))
    np.testing.assert_allclose(np.asarray(out), np.asarray(levels), atol=1e-6)


def test_odd_sizes_and_padding():
    rng = np.random.default_rng(1)
    for n in (1, 3, 127, 129, 255):
        x = jnp.asarray(rng.normal(size=(n,)), jnp.float32)
        comp = Int4Compressor(chunk=64)
        out = comp.decompress(comp.compress(x))
        assert out.shape == (n,)
        assert float(jnp.max(jnp.abs(out - x))) <= float(jnp.max(jnp.abs(x))) / 7


def test_wire_bytes_half_of_int8():
    from consensusml_tpu.compress import Int8Compressor

    shape = (4096,)
    w4 = Int4Compressor(chunk=256).wire_bytes(shape, jnp.float32)
    w8 = Int8Compressor(chunk=256).wire_bytes(shape, jnp.float32)
    # same scale overhead, half the data bytes
    assert w4 == w8 - 4096 // 2
    assert 4096 * 4 / w4 > 7  # ~8x vs dense f32


@pytest.mark.parametrize("shape", [(512,), (1000,), (64, 33)])
def test_pallas_interpret_matches_jnp(shape):
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=shape), jnp.float32)
    pj = PallasInt4Compressor(chunk=128, impl="jnp")
    pi = PallasInt4Compressor(chunk=128, impl="interpret")
    rj = pj.decompress(pj.compress(x))
    ri = pi.decompress(pi.compress(x))
    np.testing.assert_allclose(np.asarray(ri), np.asarray(rj), atol=1e-6)


def test_kernel_matches_reference_packing():
    """The fused kernel's bytes equal the jnp reference's bytes exactly
    (same nibble layout, same rounding)."""
    rng = np.random.default_rng(3)
    chunks = jnp.asarray(rng.normal(size=(48, 256)), jnp.float32)
    packed, scales = quantize_int4(chunks, interpret=True)
    ref = Int4Compressor(chunk=256).compress(chunks.reshape(-1))
    np.testing.assert_array_equal(
        np.asarray(packed).reshape(-1), np.asarray(ref.data)
    )
    np.testing.assert_allclose(
        np.asarray(scales), np.asarray(ref.scales), rtol=1e-6
    )
    out = dequantize_int4(packed, scales, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out).reshape(-1),
        np.asarray(Int4Compressor(chunk=256).decompress(ref)),
        atol=1e-6,
    )


def test_composed_topk_int4_in_choco():
    """topk+int4 drives CHOCO consensus to contraction like topk+int8."""
    from consensusml_tpu.comm import simulated
    from consensusml_tpu.consensus import ConsensusEngine, GossipConfig
    from consensusml_tpu.topology import RingTopology

    topo = RingTopology(4)
    engine = ConsensusEngine(
        GossipConfig(
            topology=topo,
            compressor=topk_int4_compressor(ratio=0.25, chunk=128, impl="jnp"),
            gamma=0.5,
        )
    )
    rng = np.random.default_rng(4)
    x = {"w": jnp.asarray(rng.normal(size=(4, 16, 16)), jnp.float32)}
    err0 = float(engine.consensus_error_simulated(x))
    # stacked params: bucketed CHOCO buffers need the worker count
    state = engine.init_state(x, world_size=4)
    w = simulated.mixing_matrix(topo)
    for _ in range(40):
        x, state = engine.round_simulated(x, state, w)
    assert float(engine.consensus_error_simulated(x)) < 0.25 * err0


def test_topk_int4_wire_halves_topk_int8_values():
    shape = (8192,)
    w4 = topk_int4_compressor(chunk=512, k=8).wire_bytes(shape, jnp.float32)
    w8 = topk_int8_compressor(chunk=512, k=8).wire_bytes(shape, jnp.float32)
    assert w4 < w8


def test_narrow_indices_reject_oversized_chunks():
    from consensusml_tpu.compress import ChunkedTopKCompressor

    with pytest.raises(ValueError, match="uint16"):
        ChunkedTopKCompressor(chunk=2**17, k_per_chunk=2)
    # opt-out works
    c = ChunkedTopKCompressor(chunk=2**17, k_per_chunk=2, narrow_indices=False)
    x = jnp.zeros(2**17).at[70000].set(5.0)
    out = c.decompress(c.compress(x))
    assert float(out[70000]) == 5.0


def test_qsgd4_unbiased_and_same_wire():
    """Stochastic int4: E[decompress(compress(x))] ~= x; identical wire
    format to the deterministic codec."""
    from consensusml_tpu.compress import QSGD4Compressor

    comp = QSGD4Compressor(chunk=128)
    assert comp.stochastic
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.uniform(-1, 1, size=(256,)), jnp.float32)
    assert comp.wire_bytes((256,), jnp.float32) == Int4Compressor(
        chunk=128
    ).wire_bytes((256,), jnp.float32)
    keys = jax.random.split(jax.random.key(0), 400)
    dec = jax.vmap(lambda k: comp.decompress(comp.compress(x, rng=k)))(keys)
    mean = jnp.mean(dec, axis=0)
    # unbiased: the Monte-Carlo mean approaches x (quant step ~ 1/7)
    np.testing.assert_allclose(np.asarray(mean), np.asarray(x), atol=0.03)
    with pytest.raises(ValueError, match="rng"):
        comp.compress(x)
