"""Parity tests for the fused BatchNorm(+ReLU) kernels.

Oracle: flax ``nn.BatchNorm`` (+ separate relu) in f32 — forward, input/
param gradients (including gradient flow *through* the batch statistics)
and the running-stat EMA must all match. The Pallas path runs under the
interpreter on CPU (tests/test_fused_bn_tpu-style on-chip checks live in
test_kernels_tpu.py).
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from consensusml_tpu.models.fused_bn import FusedBatchNorm, fused_batch_norm


def _flax_ref(x, gamma, beta, relu):
    bn = nn.BatchNorm(
        use_running_average=False, momentum=0.9, epsilon=1e-5, dtype=jnp.float32
    )
    variables = {
        "params": {"scale": gamma, "bias": beta},
        "batch_stats": {
            "mean": jnp.zeros(x.shape[-1]),
            "var": jnp.ones(x.shape[-1]),
        },
    }
    y, upd = bn.apply(variables, x.astype(jnp.float32), mutable=["batch_stats"])
    if relu:
        y = jnp.maximum(y, 0.0)
    return y, upd["batch_stats"]


@pytest.mark.parametrize("impl", ["jnp", "interpret"])
@pytest.mark.parametrize(
    "shape,relu",
    [
        ((16, 8, 8, 64), True),  # C < 128: lane-packing path
        ((4, 4, 4, 256), False),  # C >= 128, no activation
        ((512, 128), True),  # already 2-D
    ],
)
def test_forward_and_grads_match_flax(impl, shape, relu):
    rng = np.random.default_rng(0)
    c = shape[-1]
    x = jnp.asarray(rng.normal(size=shape) * 2 + 0.3, jnp.float32)
    gamma = jnp.asarray(rng.normal(size=(c,)) * 0.5 + 1.0, jnp.float32)
    beta = jnp.asarray(rng.normal(size=(c,)) * 0.1, jnp.float32)
    act = "relu" if relu else None

    def loss(fn):
        def f(x, gamma, beta):
            y = fn(x, gamma, beta)
            return jnp.sum(jnp.sin(y)), y

        return jax.value_and_grad(f, argnums=(0, 1, 2), has_aux=True)

    (l0, y0), g0 = loss(lambda *a: _flax_ref(*a, relu)[0])(x, gamma, beta)
    (l1, y1), g1 = loss(
        lambda *a: fused_batch_norm(*a, act=act, impl=impl)[0]
    )(x, gamma, beta)
    np.testing.assert_allclose(y1, y0, atol=1e-5)
    np.testing.assert_allclose(l1, l0, rtol=1e-5, atol=1e-4)
    np.testing.assert_allclose(g1[0], g0[0], atol=1e-5)
    for a, b in zip(g1[1:], g0[1:]):  # param grads: large f32 sums
        np.testing.assert_allclose(a, b, rtol=3e-4, atol=1e-3)


@pytest.mark.parametrize("impl", ["jnp", "interpret"])
def test_bf16_activations_f32_stats(impl):
    """bf16 inputs: statistics and grads accumulate in f32 (compare to an
    f32 flax reference at bf16 tolerances)."""
    rng = np.random.default_rng(1)
    x32 = rng.normal(size=(32, 4, 4, 128)).astype(np.float32)
    x = jnp.asarray(x32, jnp.bfloat16)
    gamma = jnp.ones((128,), jnp.float32)
    beta = jnp.zeros((128,), jnp.float32)
    y_ref, _ = _flax_ref(jnp.asarray(x, jnp.float32), gamma, beta, True)
    y, mean, var = fused_batch_norm(x, gamma, beta, act="relu", impl=impl)
    assert y.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(y, np.float32), np.asarray(y_ref), atol=0.05
    )
    # stats from the bf16 tensor itself, accumulated in f32
    xf = np.asarray(x, np.float32).reshape(-1, 128)
    np.testing.assert_allclose(mean, xf.mean(0), atol=1e-3)
    np.testing.assert_allclose(var, xf.var(0), rtol=2e-2, atol=1e-3)


def test_module_matches_flax_running_stats():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(8, 6, 6, 64)), jnp.float32)
    gamma = jnp.asarray(rng.normal(size=(64,)) * 0.3 + 1.0, jnp.float32)
    beta = jnp.asarray(rng.normal(size=(64,)) * 0.2, jnp.float32)
    y_ref, stats_ref = _flax_ref(x, gamma, beta, True)
    m = FusedBatchNorm(act="relu", impl="jnp")
    variables = m.init(jax.random.key(0), x)
    variables = {
        "params": {"scale": gamma, "bias": beta},
        "batch_stats": variables["batch_stats"],
    }
    y, upd = m.apply(variables, x, mutable=["batch_stats"])
    np.testing.assert_allclose(y, y_ref, atol=1e-5)
    for k in ("mean", "var"):
        np.testing.assert_allclose(
            upd["batch_stats"][k], stats_ref[k], atol=1e-5
        )


def test_eval_mode_uses_running_stats():
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(4, 5, 5, 32)), jnp.float32)
    mean = jnp.asarray(rng.normal(size=(32,)), jnp.float32)
    var = jnp.asarray(rng.uniform(0.5, 2.0, size=(32,)), jnp.float32)
    m = FusedBatchNorm(use_running_average=True, act=None, impl="jnp")
    variables = {
        "params": {"scale": jnp.ones(32), "bias": jnp.zeros(32)},
        "batch_stats": {"mean": mean, "var": var},
    }
    y = m.apply(variables, x)
    ref = (x - mean) / jnp.sqrt(var + 1e-5)
    np.testing.assert_allclose(y, ref, atol=1e-5)


def test_odd_shapes_fall_back_to_jnp():
    """Shapes the kernel grid can't tile (C=3, M odd) still work via the
    jnp path under impl='auto'/'pallas'."""
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.normal(size=(7, 3, 3, 3)), jnp.float32)
    gamma, beta = jnp.ones((3,)), jnp.zeros((3,))
    y, mean, var = fused_batch_norm(x, gamma, beta, act="relu", impl="pallas")
    y_ref, _ = _flax_ref(x, gamma, beta, True)
    np.testing.assert_allclose(y, y_ref, atol=1e-5)


def test_vmap_over_workers():
    """The stacked-worker (vmap) trainer path batches the kernels."""
    rng = np.random.default_rng(5)
    xs = jnp.asarray(rng.normal(size=(4, 16, 4, 4, 64)), jnp.float32)
    gammas = jnp.asarray(rng.normal(size=(4, 64)) * 0.2 + 1.0, jnp.float32)
    betas = jnp.zeros((4, 64), jnp.float32)

    def one(x, g, b):
        y, mean, var = fused_batch_norm(x, g, b, act="relu", impl="interpret")
        return y, mean

    ys, means = jax.vmap(one)(xs, gammas, betas)
    for i in range(4):
        y_ref, _ = _flax_ref(xs[i], gammas[i], betas[i], True)
        np.testing.assert_allclose(ys[i], y_ref, atol=1e-5)


def test_resnet_fused_impl_matches_flax_impl():
    """A full ResNet-18 forward/backward agrees between norm_impl='flax'
    and the fused custom-VJP path (f32, CIFAR stem)."""
    from consensusml_tpu.models import resnet_init, resnet_loss_fn, resnet18

    rng = np.random.default_rng(7)
    batch = {
        "image": jnp.asarray(rng.normal(size=(8, 32, 32, 3)), jnp.float32),
        "label": jnp.asarray(rng.integers(0, 10, size=(8,)), jnp.int32),
    }
    losses, grads = [], []
    for impl in ("flax", "jnp"):
        model = resnet18(dtype=jnp.float32, norm_impl=impl)
        params, mstate = resnet_init(model, (1, 32, 32, 3))(jax.random.key(0))
        loss_fn = resnet_loss_fn(model)
        (l, _), g = jax.value_and_grad(loss_fn, has_aux=True)(
            params, mstate, batch, jax.random.key(1)
        )
        losses.append(float(l))
        grads.append(g)
    assert abs(losses[0] - losses[1]) < 1e-4
    # param trees have different module names (BatchNorm vs FusedBatchNorm)
    # but identical leaf count and matching gradient norms
    l0 = sorted(np.linalg.norm(np.asarray(a)) for a in jax.tree.leaves(grads[0]))
    l1 = sorted(np.linalg.norm(np.asarray(a)) for a in jax.tree.leaves(grads[1]))
    np.testing.assert_allclose(l1, l0, rtol=1e-3, atol=1e-5)


def test_grad_flows_through_statistics():
    """dx must include the -mean(g) - xhat*mean(g*xhat) terms: for
    y = BN(x) (gamma=1, beta=0, no relu), sum(dL/dx) over the batch is
    ~0 for any dL/dy because the output is mean-centered."""
    rng = np.random.default_rng(6)
    x = jnp.asarray(rng.normal(size=(64, 128)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(128,)), jnp.float32)

    def f(x):
        y, _, _ = fused_batch_norm(
            x, jnp.ones(128), jnp.zeros(128), act=None, impl="interpret"
        )
        return jnp.sum(y * w)

    dx = jax.grad(f)(x)
    np.testing.assert_allclose(dx.sum(axis=0), np.zeros(128), atol=1e-4)
