"""Elastic world membership via checkpoint resize (utils.elastic).

SURVEY.md §5 "elastic recovery": the TPU design has no in-flight
join/leave (workers are mesh shards of one program), so membership
change happens at the checkpoint boundary — these tests pin the resize
semantics and the end-to-end --resume --workers path.
"""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from consensusml_tpu.compress import topk_int8_compressor
from consensusml_tpu.consensus import GossipConfig
from consensusml_tpu.data import SyntheticClassification, round_batches
from consensusml_tpu.models import MLP, mlp_loss_fn
from consensusml_tpu.topology import RingTopology
from consensusml_tpu.train import (
    LocalSGDConfig,
    init_stacked_state,
    make_simulated_train_step,
)
from consensusml_tpu.utils import resize_state

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cfg(world, compressor=None):
    return LocalSGDConfig(
        gossip=GossipConfig(
            topology=RingTopology(world),
            compressor=compressor,
            gamma=0.5 if compressor else 1.0,
        ),
        optimizer=optax.adam(1e-2),
        h=1,
    )


def _trained_state(world=4, rounds=3, compressor=None):
    model = MLP(hidden=16)
    cfg = _cfg(world, compressor)
    step = make_simulated_train_step(cfg, mlp_loss_fn(model))
    state = init_stacked_state(
        cfg,
        lambda r: model.init(r, jnp.zeros((1, 28, 28, 1)))["params"],
        jax.random.key(0),
        world,
    )
    data = SyntheticClassification(n=256)
    for batch in round_batches(data, world, 1, 8, rounds):
        state, _ = step(state, batch)
    return model, cfg, state, data


def test_grow_joiners_start_at_consensus_mean():
    model, cfg, state, _ = _trained_state(world=4)
    new_cfg = _cfg(6)
    resized = resize_state(new_cfg, state, 6, rng=jax.random.key(7))
    assert resized.step.shape == (6,)
    mean = jax.tree.map(
        lambda x: np.mean(np.asarray(x, np.float32), axis=0), state.params
    )
    for leaf, m in zip(jax.tree.leaves(resized.params), jax.tree.leaves(mean)):
        np.testing.assert_allclose(np.asarray(leaf[4]), m, rtol=1e-6)
        np.testing.assert_allclose(np.asarray(leaf[5]), m, rtol=1e-6)
    # survivors keep their exact replicas
    for leaf, old in zip(jax.tree.leaves(resized.params), jax.tree.leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(leaf[:4]), np.asarray(old))
    # joiners' rng streams are fresh and distinct
    keys = np.asarray(jax.random.key_data(resized.rng))
    assert not np.array_equal(keys[4], keys[5])
    # step counter carries the round count to joiners
    assert int(resized.step[5]) == int(state.step[0])


def test_shrink_keeps_survivor_replicas_exactly():
    model, cfg, state, _ = _trained_state(world=6)
    new_cfg = _cfg(4)
    resized = resize_state(new_cfg, state, 4)
    assert resized.step.shape == (4,)
    for leaf, old in zip(jax.tree.leaves(resized.params), jax.tree.leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(leaf), np.asarray(old)[:4])
    for leaf, old in zip(
        jax.tree.leaves(resized.opt_state), jax.tree.leaves(state.opt_state)
    ):
        np.testing.assert_array_equal(np.asarray(leaf), np.asarray(old)[:4])


def test_resize_resets_choco_state_at_new_world():
    comp = topk_int8_compressor(ratio=0.5, chunk=128)
    model, cfg, state, _ = _trained_state(world=4, compressor=comp)
    assert state.gossip is not None
    new_cfg = _cfg(6, compressor=comp)
    resized = resize_state(new_cfg, state, 6, rng=jax.random.key(1))
    for leaf in jax.tree.leaves(resized.gossip.xhat):
        assert leaf.shape[0] == 6
        np.testing.assert_array_equal(np.asarray(leaf), 0.0)


def test_training_continues_after_resize_both_ways():
    for new_world in (6, 2):
        model, cfg, state, data = _trained_state(world=4)
        new_cfg = _cfg(new_world)
        resized = resize_state(new_cfg, state, new_world, rng=jax.random.key(2))
        step = make_simulated_train_step(new_cfg, mlp_loss_fn(model))
        losses = []
        start = int(resized.step[0])
        for batch in round_batches(data, new_world, 1, 8, 10, start=start):
            resized, m = step(resized, batch)
            losses.append(float(m["loss"]))
            assert np.isfinite(losses[-1])
        assert losses[-1] < losses[0] * 1.5  # trains, no blow-up


def test_noop_resize_returns_state():
    model, cfg, state, _ = _trained_state(world=4)
    assert resize_state(cfg, state, 4) is state
    with pytest.raises(ValueError, match="positive"):
        resize_state(cfg, state, 0)


@pytest.mark.slow
def test_cli_elastic_resume(tmp_path):
    """End-to-end: checkpoint at 4 workers, resume at 6."""
    ck = str(tmp_path / "ck")

    def run(extra):
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "train.py"),
             "--config", "mnist_mlp", "--device", "cpu", *extra],
            capture_output=True, text=True, timeout=600, cwd=REPO,
            env={**os.environ, "JAX_PLATFORMS": ""},
        )

    r1 = run(["--rounds", "3", "--checkpoint-dir", ck])
    assert r1.returncode == 0, r1.stderr[-800:]
    ckpt = os.path.join(ck, "step_3")
    assert os.path.exists(os.path.join(ckpt, "cml_meta.json"))
    ck2 = str(tmp_path / "ck6")
    r2 = run(["--rounds", "2", "--workers", "6", "--resume", ckpt,
              "--checkpoint-dir", ck2])
    assert r2.returncode == 0, r2.stderr[-800:]
    assert "elastic resume: 4 -> 6 workers" in r2.stdout
    assert "resumed from" in r2.stdout and "at round 3" in r2.stdout
    assert "final:" in r2.stdout
    # forgetting --workers on a non-default-world checkpoint must ADOPT
    # the checkpoint's world, not silently shrink it back to the default
    r3 = run(["--rounds", "1", "--resume", os.path.join(ck2, "step_5")])
    assert r3.returncode == 0, r3.stderr[-800:]
    assert "workers=6" in r3.stdout
    assert "elastic resume" not in r3.stdout


def test_resize_resets_pushsum_mass():
    model, cfg, state, _ = _trained_state(world=4)
    import dataclasses

    ps_cfg = dataclasses.replace(
        _cfg(6), gossip=dataclasses.replace(_cfg(6).gossip, push_sum=True)
    )
    resized = resize_state(ps_cfg, state, 6, rng=jax.random.key(5))
    assert resized.gossip is not None
    np.testing.assert_array_equal(np.asarray(resized.gossip.w), np.ones(6))


def test_restore_resets_old_gossip_layout(tmp_path):
    """A checkpoint whose ChocoState has an OLD leaf layout (e.g.
    pre-compress_filter="auto" runs tracked model_state leaves) must
    restore with gossip state RESET instead of failing structurally
    (ADVICE r3); everything else restores exactly."""
    import warnings

    from consensusml_tpu.consensus.engine import ChocoState
    from consensusml_tpu.utils import restore_state, save_state

    codec = topk_int8_compressor(chunk=128, k=8)
    _, _, state, _ = _trained_state(world=4, rounds=2, compressor=codec)
    old_gossip = ChocoState(
        xhat={"params": state.gossip.xhat, "model_state": {"bn": jnp.ones((4, 3))}},
        s={"params": state.gossip.s, "model_state": {"bn": jnp.ones((4, 3))}},
    )
    path = save_state(str(tmp_path / "old_layout"), state._replace(gossip=old_gossip))

    _, _, template, _ = _trained_state(world=4, rounds=0, compressor=codec)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        restored = restore_state(path, template)
    assert any("gossip" in str(w.message) and "RESET" in str(w.message) for w in caught)
    # gossip reset to the template's fresh zeros
    assert all((np.asarray(l) == 0).all() for l in jax.tree.leaves(restored.gossip))
    # params/step restored from the checkpoint, not the template
    np.testing.assert_array_equal(np.asarray(restored.step), np.asarray(state.step))
    for a, b in zip(jax.tree.leaves(restored.params), jax.tree.leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_restore_still_fails_on_non_gossip_mismatch(tmp_path):
    """The gossip-reset fallback must not mask real template mismatches
    (e.g. optimizer state from different LR flags)."""
    import dataclasses

    from consensusml_tpu.utils import restore_state, save_state

    _, _, state, _ = _trained_state(world=4, rounds=1)
    path = save_state(str(tmp_path / "ok_layout"), state)

    bad_cfg = dataclasses.replace(
        _cfg(4), optimizer=optax.chain(optax.clip_by_global_norm(1.0), optax.adam(1e-2))
    )
    model = MLP(hidden=16)
    bad_template = init_stacked_state(
        bad_cfg,
        lambda r: model.init(r, jnp.zeros((1, 28, 28, 1)))["params"],
        jax.random.key(0),
        4,
    )
    with pytest.raises(Exception):
        restore_state(path, bad_template)
