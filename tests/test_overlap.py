"""Overlap (combine-then-adapt) gossip: z_{k+1} = W z_k + u_k.

The correction ``(W - I) z`` is computed from pre-inner-loop params and
applied one round late, so the communication is schedulable UNDER the H
local steps. With zero inner updates the recurrence is plain gossip
``z <- W z`` — that exactness anchors the tests.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from consensusml_tpu.comm import WorkerMesh, simulated
from consensusml_tpu.consensus import GossipConfig, OverlapState
from consensusml_tpu.data import SyntheticClassification, round_batches
from consensusml_tpu.models import MLP, mlp_loss_fn
from consensusml_tpu.topology import RingTopology, TorusTopology
from consensusml_tpu.train import (
    LocalSGDConfig,
    init_stacked_state,
    make_collective_train_step,
    make_simulated_train_step,
)

WORLD = 8


def _cfg(topo, lr=0.05, h=1, overlap=True):
    return LocalSGDConfig(
        gossip=GossipConfig(topology=topo, overlap=overlap),
        optimizer=optax.sgd(lr),
        h=h,
    )


def _batches(cfg, rounds, batch=16, seed=0):
    data = SyntheticClassification(n=256, image_shape=(8, 8, 1))
    return round_batches(data, WORLD, cfg.h, batch, rounds, seed=seed)


def test_zero_lr_reduces_to_plain_gossip():
    """With no inner updates, overlap mode IS x <- W x: params match the
    mixing-matrix power exactly and consensus error contracts at the
    spectral rate."""
    topo = RingTopology(WORLD)
    cfg = _cfg(topo, lr=0.0)
    step = make_simulated_train_step(cfg, mlp_loss_fn(MLP(hidden=8)))
    state = init_stacked_state(
        cfg, lambda r: MLP(hidden=8).init(r, jnp.zeros((1, 8, 8, 1)))["params"],
        jax.random.key(0), WORLD,
    )
    x0 = jax.tree.map(jnp.copy, state.params)
    w = np.asarray(simulated.mixing_matrix(topo))
    rounds = 6
    for batch in _batches(cfg, rounds):
        state, metrics = step(state, batch)
    # after k rounds the params hold W^{k-1} x0: round k's correction is
    # still in flight in the carry (that pipeline lag IS the overlap)
    wk = np.linalg.matrix_power(w, rounds - 1)
    expect = jax.tree.map(
        lambda x: jnp.einsum("ij,j...->i...", jnp.asarray(wk), x), x0
    )
    for got, want in zip(jax.tree.leaves(state.params), jax.tree.leaves(expect)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_collective_matches_simulated():
    topo = TorusTopology(2, 4)
    cfg = _cfg(topo, lr=0.05, h=2)
    loss_fn = mlp_loss_fn(MLP(hidden=8))
    init = lambda r: MLP(hidden=8).init(r, jnp.zeros((1, 8, 8, 1)))["params"]
    sim_step = make_simulated_train_step(cfg, loss_fn)
    col_step = make_collective_train_step(
        cfg, loss_fn, WorkerMesh.create(topo, devices=jax.devices()[:WORLD])
    )
    sim = init_stacked_state(cfg, init, jax.random.key(0), WORLD)
    col = jax.tree.map(jnp.copy, sim)
    for batch in _batches(cfg, 4):
        sim, sm = sim_step(sim, batch)
        col, cm = col_step(col, batch)
    np.testing.assert_allclose(
        float(sm["consensus_error"]), float(cm["consensus_error"]), rtol=1e-4
    )
    for a, b in zip(jax.tree.leaves(sim.params), jax.tree.leaves(col.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_corrections_sum_to_zero():
    """Mean-exactness: for doubly stochastic W the per-worker corrections
    cancel, so the network mean evolves by local updates alone."""
    topo = RingTopology(WORLD)
    cfg = _cfg(topo, lr=0.05)
    step = make_simulated_train_step(cfg, mlp_loss_fn(MLP(hidden=8)))
    state = init_stacked_state(
        cfg, lambda r: MLP(hidden=8).init(r, jnp.zeros((1, 8, 8, 1)))["params"],
        jax.random.key(1), WORLD,
    )
    for batch in _batches(cfg, 3):
        state, _ = step(state, batch)
    assert isinstance(state.gossip, OverlapState)
    for leaf in jax.tree.leaves(state.gossip.correction):
        total = np.asarray(jnp.sum(leaf, axis=0))
        np.testing.assert_allclose(total, np.zeros_like(total), atol=1e-4)


def test_training_converges_with_overlap():
    topo = RingTopology(WORLD)
    cfg = _cfg(topo, lr=0.1, h=2)
    step = make_simulated_train_step(cfg, mlp_loss_fn(MLP(hidden=16)))
    state = init_stacked_state(
        cfg, lambda r: MLP(hidden=16).init(r, jnp.zeros((1, 8, 8, 1)))["params"],
        jax.random.key(2), WORLD,
    )
    losses, errs = [], []
    for batch in _batches(cfg, 30):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
        errs.append(float(m["consensus_error"]))
    assert np.mean(losses[-5:]) < 0.5 * np.mean(losses[:5])
    assert errs[-1] < errs[0]


def test_overlap_rejects_incompatible_configs():
    from consensusml_tpu.compress import topk_int8_compressor
    from consensusml_tpu.consensus import FaultConfig
    from consensusml_tpu.train import SlowMoConfig

    topo = RingTopology(WORLD)
    with pytest.raises(NotImplementedError, match="compression"):
        GossipConfig(
            topology=topo, overlap=True,
            compressor=topk_int8_compressor(ratio=0.1, chunk=128),
        )
    with pytest.raises(NotImplementedError, match="push-sum"):
        GossipConfig(topology=topo, overlap=True, push_sum=True)
    with pytest.raises(NotImplementedError, match="fault"):
        GossipConfig(
            topology=topo, overlap=True, faults=FaultConfig(drop_prob=0.1)
        )
    with pytest.raises(NotImplementedError, match="SlowMo"):
        LocalSGDConfig(
            gossip=GossipConfig(topology=topo, overlap=True),
            optimizer=optax.sgd(0.1),
            outer=SlowMoConfig(beta=0.5),
        )


def test_time_varying_overlap_backends_agree():
    """One-peer exponential (time-varying): the phase a correction is
    computed with must match across backends round for round."""
    from consensusml_tpu.topology import OnePeerExponentialTopology

    topo = OnePeerExponentialTopology(WORLD)
    cfg = _cfg(topo, lr=0.05, h=1)
    loss_fn = mlp_loss_fn(MLP(hidden=8))
    init = lambda r: MLP(hidden=8).init(r, jnp.zeros((1, 8, 8, 1)))["params"]
    sim_step = make_simulated_train_step(cfg, loss_fn)
    col_step = make_collective_train_step(
        cfg, loss_fn, WorkerMesh.create(topo, devices=jax.devices()[:WORLD])
    )
    sim = init_stacked_state(cfg, init, jax.random.key(3), WORLD)
    col = jax.tree.map(jnp.copy, sim)
    # > one full period so every phase's correction is exercised
    for batch in _batches(cfg, topo.period + 2, seed=3):
        sim, sm = sim_step(sim, batch)
        col, cm = col_step(col, batch)
    np.testing.assert_allclose(
        float(sm["consensus_error"]), float(cm["consensus_error"]), rtol=1e-4
    )
    for a, b in zip(jax.tree.leaves(sim.params), jax.tree.leaves(col.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_hierarchical_overlap_backends_agree():
    """Overlap over the multi-slice topology: inner-ring rounds and the
    1-in-K inter-slice round each produce corrections with THEIR phase's
    W, applied one round later — backends must agree across the period."""
    from consensusml_tpu.topology import HierarchicalTopology

    topo = HierarchicalTopology(slices=2, inner=4, outer_every=2)
    cfg = _cfg(topo, lr=0.05, h=1)
    loss_fn = mlp_loss_fn(MLP(hidden=8))
    init = lambda r: MLP(hidden=8).init(r, jnp.zeros((1, 8, 8, 1)))["params"]
    sim_step = make_simulated_train_step(cfg, loss_fn)
    col_step = make_collective_train_step(
        cfg, loss_fn, WorkerMesh.create(topo, devices=jax.devices()[:WORLD])
    )
    sim = init_stacked_state(cfg, init, jax.random.key(4), WORLD)
    col = jax.tree.map(jnp.copy, sim)
    for batch in _batches(cfg, 2 * topo.period, seed=4):
        sim, sm = sim_step(sim, batch)
        col, cm = col_step(col, batch)
    np.testing.assert_allclose(
        float(sm["consensus_error"]), float(cm["consensus_error"]), rtol=1e-4
    )
    for a, b in zip(jax.tree.leaves(sim.params), jax.tree.leaves(col.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)
