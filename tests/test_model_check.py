"""Model-checking pass + lifecycle lint suite (ISSUE 19 acceptance).

Three layers, mirroring the pass's own argument for why it can be
trusted:

1. checker mechanics — state hashing, exact bound semantics (a state
   reached again at a shallower depth is re-expanded), BFS-minimal
   counterexamples, replay;
2. the shipped protocol models — the three correct models hold their
   invariants over their ENTIRE finite reachable state space, and every
   seeded-bug fixture model is refuted with a minimal trace (PR 15
   detector-broken pattern: a fixture the checker cannot refute fails
   the pass);
3. conformance — recorded traces from the REAL classes (randomized
   BlockPool churn, a live preempt + hot-swap engine run, membership
   pin/advance) replay as valid paths of the abstract models, tying the
   abstractions back to the code they claim to describe.

The lifecycle escape lint and the locks unlocked-read rule ride along
with their own seeded fixtures.
"""

import os

import numpy as np
import pytest

from consensusml_tpu.analysis.model import (
    CheckResult,
    ConformanceError,
    IllegalAction,
    check_model,
    replay,
    successors,
)

pytestmark = pytest.mark.analysis

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# checker mechanics (toy models)
# ---------------------------------------------------------------------------


class _Graph:
    """Explicit transition-table model for exercising the checker."""

    name = "toy-graph"
    subject = "tests/test_model_check.py"

    def __init__(self, edges, bad=()):
        self.edges = edges  # state -> [(label_head, next_state)]
        self.bad = frozenset(bad)

    def initial(self):
        return "0"

    def labels(self, state):
        return [(head,) for head, _ in self.edges.get(state, [])]

    def apply(self, state, label):
        for head, nxt in self.edges.get(state, []):
            if head == label[0]:
                return nxt
        raise IllegalAction(f"{label[0]} not enabled in {state}")

    def invariant(self, state):
        return f"reached bad state {state}" if state in self.bad else None


# deep path 0-A-C-T, shallow path 0-B-T, and U behind T: U is only
# reachable within depth 3 via the SHALLOW path, so finding it proves
# the checker re-expands T when the 2-step path arrives after the
# 3-step one (DFS pops toA's branch first given this label order)
_DIAMOND = {
    "0": [("toB", "B"), ("toA", "A")],
    "A": [("ac", "C")],
    "C": [("ct", "T")],
    "B": [("bt", "T")],
    "T": [("tu", "U")],
}


def test_bounded_dfs_reexpands_shallower_revisits():
    res = check_model(_Graph(_DIAMOND), max_depth=3)
    assert res.ok and res.states == 6 and res.hit_bound
    # at depth 2 U is out of reach down every path; T's successor makes
    # the truncation observable
    res2 = check_model(_Graph(_DIAMOND), max_depth=2)
    assert res2.ok and res2.states == 5 and res2.hit_bound


def test_unbounded_search_exhausts_and_reports_no_bound():
    res = check_model(_Graph(_DIAMOND), max_depth=None)
    assert res.ok and res.states == 6 and not res.hit_bound
    assert res.max_depth is None


def test_counterexample_is_bfs_minimal_with_matching_message():
    res = check_model(_Graph(_DIAMOND, bad={"U"}), max_depth=4)
    assert not res.ok
    # the minimal route is via B (3 steps), even though DFS explores
    # the 4-step A route
    assert res.trace == (("toB",), ("bt",), ("tu",))
    assert res.violation == "reached bad state U"
    assert "toB ; bt ; tu" == res.format_trace()


def test_state_hashing_counts_distinct_states_once():
    # two routes into T must not double-count it
    res = check_model(_Graph(_DIAMOND), max_depth=None)
    assert res.states == len({"0", "A", "B", "C", "T", "U"})


def test_successors_filters_illegal_actions():
    class _Gated(_Graph):
        def labels(self, state):
            return [("nope",)] + super().labels(state)

    succ = list(successors(_Gated(_DIAMOND), "0"))
    assert [(l[0], s) for l, s in succ] == [("toB", "B"), ("toA", "A")]


def test_max_states_overflow_raises():
    class _Unbounded:
        name = "counter"
        subject = "x"

        def initial(self):
            return 0

        def labels(self, state):
            return [("inc",)]

        def apply(self, state, label):
            return state + 1

        def invariant(self, state):
            return None

    with pytest.raises(RuntimeError, match="state space exceeds"):
        check_model(_Unbounded(), max_depth=None, max_states=50)


def test_replay_accepts_valid_path_and_names_failing_step():
    m = _Graph(_DIAMOND)
    assert replay(m, [("toB",), ("bt",), ("tu",)]) == "U"
    with pytest.raises(ConformanceError, match="step 1 ac"):
        replay(m, [("toB",), ("ac",)])
    with pytest.raises(ConformanceError, match="step 2 tu"):
        replay(_Graph(_DIAMOND, bad={"U"}), [("toB",), ("bt",), ("tu",)])


def test_violating_initial_state_reported_without_search():
    res = check_model(_Graph(_DIAMOND, bad={"0"}))
    assert not res.ok and res.trace == () and "bad state 0" in res.violation
    assert isinstance(res, CheckResult)


# ---------------------------------------------------------------------------
# the shipped protocol models
# ---------------------------------------------------------------------------


def test_shipped_models_hold_over_their_entire_state_space():
    from consensusml_tpu.analysis import protocol_models as pm

    for spec in pm.builtin_specs():
        res = check_model(
            spec.model, max_depth=spec.max_depth, max_states=spec.max_states
        )
        assert res.ok, (spec.model.name, res.violation, res.format_trace())
        # max_depth=None: full reachability, nothing truncated — the
        # invariants are proven over the whole space, not a prefix
        assert not res.hit_bound, spec.model.name
        assert res.states > 100, (spec.model.name, res.states)


def test_every_seeded_bug_fixture_is_refuted_with_minimal_trace():
    from consensusml_tpu.analysis import protocol_models as pm

    for spec in pm.fixture_specs():
        res = check_model(spec.model, max_depth=spec.max_depth)
        assert not res.ok and res.trace, spec.model.name
        assert len(res.trace) <= spec.max_depth
        # the trace really is executable and really does end in the
        # violation: replay the model's own counterexample
        with pytest.raises(ConformanceError, match="invariant violated"):
            replay(spec.model, res.trace)
        # and it is MINIMAL: every proper prefix is violation-free
        replay(spec.model, res.trace[:-1])


def test_run_builtin_clean_and_detector_broken_guard(monkeypatch):
    from consensusml_tpu.analysis import protocol_models as pm

    assert pm.run_builtin() == []

    # neuter the fixture set: a "fixture" that is actually correct must
    # surface as detector-broken, never as silently green (PR 15)
    monkeypatch.setattr(
        pm, "fixture_specs",
        lambda: [pm.ModelSpec(
            pm.PoolModel(), max_depth=4, expect_violation=True,
        )],
    )
    got = pm.run_builtin()
    assert [f.rule for f in got] == ["detector-broken"]
    assert got[0].counterexample == ()


def test_invariant_violation_finding_carries_counterexample(monkeypatch):
    import json

    from consensusml_tpu.analysis import protocol_models as pm
    from consensusml_tpu.analysis import to_json

    # ship a buggy model as if it were a real one: the finding must
    # carry the minimal action trace, and --json must serialize it
    monkeypatch.setattr(
        pm, "builtin_specs",
        lambda: [pm.ModelSpec(pm.DoubleFreePoolModel(), max_depth=8)],
    )
    monkeypatch.setattr(pm, "fixture_specs", lambda: [])
    got = pm.run_builtin()
    assert len(got) == 1 and got[0].rule == "invariant-violated"
    assert got[0].counterexample, got[0]
    doc = json.loads(to_json(got, [], [], passes_run=["model"]))
    (f,) = doc["findings"]
    assert f["counterexample"] == list(got[0].counterexample)
    # clean findings omit the field entirely
    from consensusml_tpu.analysis import Finding

    assert "counterexample" not in Finding(
        "model", "r", "p", "s", "d", "m"
    ).to_dict()


# ---------------------------------------------------------------------------
# lifecycle escape lint
# ---------------------------------------------------------------------------


def test_lifecycle_seeded_fixture_fires_and_package_is_clean():
    from consensusml_tpu.analysis import lifecycle

    got = lifecycle.lint_source(lifecycle._LEAK_FIXTURE, "<fx>")
    assert [f.rule for f in got] == ["leak-on-exception"]
    assert got[0].detail == "pool.alloc"

    pkg = lifecycle.lint_paths(
        [os.path.join(REPO, "consensusml_tpu")], REPO
    )
    assert pkg == [], [f.id for f in pkg]


def test_lifecycle_self_test_reports_broken_detector(monkeypatch):
    from consensusml_tpu.analysis import lifecycle

    monkeypatch.setattr(lifecycle, "_LEAK_FIXTURE", "def f():\n    pass\n")
    got = lifecycle.lint_paths([], REPO)
    assert [f.rule for f in got] == ["detector-broken"]


def test_lifecycle_try_finally_and_handler_release_cover():
    from consensusml_tpu.analysis import lifecycle

    clean = """
def a(self, s):
    self._pool.begin(s)
    try:
        self.run(s)
    finally:
        self._pool.release(s)

def b(self, s):
    self._pool.begin(s)
    try:
        self.run(s)
    except Exception:
        self._pool.release(s)
        raise
"""
    assert lifecycle.lint_source(clean, "<fx>") == []


def test_lifecycle_handle_rules_flag_leak_and_exempt_transfer():
    from consensusml_tpu.analysis import lifecycle

    leak = """
def f(p):
    fh = open(p)
    data = fh.read()
    fh.close()
    return data
"""
    got = lifecycle.lint_source(leak, "<fx>")
    assert [f.rule for f in got] == ["handle-leak"], got

    exempt = """
def g(p):
    fh = open(p)
    return fh

def h(self, p):
    self._fh = open(p)

def i(p):
    with open(p) as fh:
        return fh.read()
"""
    assert lifecycle.lint_source(exempt, "<fx>") == []


# ---------------------------------------------------------------------------
# locks: unlocked-read rule
# ---------------------------------------------------------------------------

_LOCKS_FIXTURE = '''
@guarded_by("_lock", "_generation", "_staged")
class Watcher:
    def __init__(self):
        self._lock = threading.Lock()
        self._generation = 0
        self._staged = None

class Engine:
    def __init__(self):
        self._watcher = Watcher()

    def bad(self):
        return self._watcher._generation

    def ok_under_owners_lock(self):
        with self._watcher._lock:
            return self._watcher._generation

    def ok_method_call(self):
        return self._watcher.take()
'''


def test_unlocked_read_flags_cross_class_access_only():
    import ast

    from consensusml_tpu.analysis import locks

    guarded = locks._guarded_classes_in_tree(ast.parse(_LOCKS_FIXTURE))
    assert guarded == {"Watcher": {"_generation": "_lock", "_staged": "_lock"}}
    got = [
        f for f in locks.lint_source(_LOCKS_FIXTURE, "<fx>", guarded)
        if f.rule == "unlocked-read"
    ]
    assert [(f.symbol, f.detail) for f in got] == [
        ("Engine.bad", "_generation")
    ]
    # without the package map the per-file rules still run, silently
    # skipping the cross-class scan
    assert locks.lint_source(_LOCKS_FIXTURE, "<fx>") != None  # noqa: E711


def test_unlocked_read_package_scan_is_clean():
    from consensusml_tpu.analysis import locks

    got = [
        f
        for f in locks.lint_paths([os.path.join(REPO, "consensusml_tpu")], REPO)
        if f.rule == "unlocked-read"
    ]
    assert got == [], [f.id for f in got]


# ---------------------------------------------------------------------------
# conformance: recorded traces of the REAL classes replay in the models
# ---------------------------------------------------------------------------


def test_pool_churn_trace_replays_and_free_lists_agree():
    """The PR 17 randomized churn workload, recorded: every begin /
    adopt / extend / pin / unpin / shrink / release the real BlockPool
    performs is a legal model action in sequence, and at the end the
    model's LIFO free stack equals the pool's actual free list —
    block-id-exact conformance, not just shape conformance."""
    from consensusml_tpu.analysis.conformance import (
        RecordingPool,
        replay_pool_trace,
    )
    from consensusml_tpu.serve.pool import blocks as P

    rng = np.random.default_rng(7)
    pool = RecordingPool(num_slots=8, max_len=20, block_size=4, num_blocks=25)
    live: set[int] = set()
    pinned: list[int] = []
    for _ in range(400):
        op = rng.integers(0, 6)
        if op == 0 and len(live) < pool.num_slots:
            slot = next(s for s in range(pool.num_slots) if s not in live)
            pool.begin(slot)
            if live and rng.random() < 0.5:
                donor = int(rng.choice(sorted(live)))
                owned = pool.owned(donor)
                k = int(rng.integers(1, min(len(owned), 3) + 1))
                pool.adopt(slot, owned[:k])
            try:
                pool.extend(slot, int(rng.integers(1, 3)))
            except P.NoFreeBlocks:
                pool.release(slot)
            else:
                live.add(slot)
        elif op == 1 and live:
            slot = int(rng.choice(sorted(live)))
            if len(pool.owned(slot)) < pool.blocks_per_slot:
                try:
                    pool.extend(slot)
                except P.NoFreeBlocks:
                    pass
        elif op == 2 and live:
            slot = int(rng.choice(sorted(live)))
            pool.shrink(slot, int(rng.integers(1, len(pool.owned(slot)) + 1)))
        elif op == 3 and live:
            slot = int(rng.choice(sorted(live)))
            b = int(rng.choice(pool.owned(slot)))
            pool.pin(b)
            pinned.append(b)
        elif op == 4 and pinned:
            pool.unpin(pinned.pop(int(rng.integers(0, len(pinned)))))
        elif op == 5 and live:
            slot = int(rng.choice(sorted(live)))
            pool.release(slot)
            live.discard(slot)
        pool.check()
    for b in pinned:
        pool.unpin(b)
    for slot in sorted(live):
        pool.release(slot)
    pool.check()

    assert len(pool.trace) > 200, "churn too small to mean anything"
    final = replay_pool_trace(pool)
    assert list(final[0]) == list(pool._free)


def test_pool_trace_with_seeded_drift_fails_replay():
    """Conformance is falsifiable: corrupt one recorded block id and
    replay rejects the trace at that step."""
    from consensusml_tpu.analysis.conformance import (
        RecordingPool,
        replay_pool_trace,
    )

    pool = RecordingPool(num_slots=2, max_len=20, block_size=4, num_blocks=8)
    pool.begin(0)
    pool.extend(0, 2)
    pool.release(0)
    # the real pool popped (1, 2); claim it popped (1, 5)
    pool.trace[1] = ("extend", 0, (1, 5))
    with pytest.raises(ConformanceError, match="step 1"):
        replay_pool_trace(pool)


def test_membership_pin_advance_trace_replays():
    from consensusml_tpu.analysis.conformance import (
        RecordingMembership,
        replay_membership_trace,
    )
    from consensusml_tpu.topology.topologies import RingTopology

    mc = RecordingMembership(RingTopology(4))
    v0 = mc.pin()
    mc.advance()
    v1 = mc.pin()
    mc.advance()
    mc.release(v0)  # round against epoch 0 completes AFTER two advances
    mc.release(v1)
    final = replay_membership_trace(mc)
    assert final is not None
    # no residual pinned rounds
    assert not mc.pinned_epochs()


@pytest.mark.serving
def test_engine_preempt_hotswap_run_replays_in_request_model(
    tmp_path, monkeypatch
):
    """The acceptance e2e: a REAL engine run with recompute preemption
    (8 streams vs 4 slots and 10 blocks) and a live hot-swap generation
    flip, recorded through the engine's own wide-event request traces,
    replays as a valid path of the request-lifecycle model — slot
    aliasing, readmission-continuation accounting, and generation
    monotonicity all checked step by step."""
    import time

    import jax
    import jax.numpy as jnp

    from consensusml_tpu.analysis.conformance import replay_request_registry
    from consensusml_tpu.models.gpt2 import GPT2Config, GPT2LM
    from consensusml_tpu.obs import requests as rq
    from consensusml_tpu.serve import Engine, ServeConfig
    from consensusml_tpu.serve.export import (
        _write_meta,
        bump_generation,
        serving_meta,
    )
    from consensusml_tpu.serve.pool.hotswap import GenerationWatcher

    # a fresh registry so the recording covers exactly this run
    monkeypatch.setattr(rq, "_GLOBAL", rq.RequestTraceRegistry())

    model = GPT2LM(
        config=GPT2Config(
            vocab_size=64, hidden=32, layers=2, heads=2, max_len=32,
            dropout=0.0,
        )
    )
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    art = str(tmp_path / "art")
    os.makedirs(art)
    _write_meta(art, {"generation": 1, "config_name": "model-check-fixture"})

    eng = Engine(
        model, params,
        ServeConfig(num_slots=4, max_len=32, max_new_tokens=24, num_blocks=10),
    )
    loader_calls = []

    def loader(path):
        loader_calls.append(path)
        return serving_meta(path), params, None

    eng._watcher = GenerationWatcher(
        art, current_generation=0, poll_s=0.01, loader=loader
    )
    try:
        rng = np.random.default_rng(3)
        handles = [
            eng.submit(rng.integers(0, 63, size=n).tolist(), 24)
            for n in (3, 7, 8, 8, 4, 6, 8, 5)
        ]
        bump_generation(art)  # swap while the waves are in flight
        for h in handles:
            assert len(h.result(timeout=180).tokens) == 24
        deadline = time.monotonic() + 30
        while eng.generation < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
    finally:
        eng.shutdown(drain=True, timeout=60)

    stats = eng.stats()
    assert stats["evictions"] >= 1, stats  # preemption really happened
    assert eng.generation >= 1 and loader_calls  # hot-swap really flipped

    final = replay_request_registry(rq._GLOBAL, n_slots=4)
    assert final is not None
