"""Blockwise (flash-style) attention parity vs the dense reference
(VERDICT weak #7: long-seq configs need O(S) activation memory)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from consensusml_tpu.models.attention import (
    blockwise_attention,
    dot_product_attention,
)


def _qkv(rng, b, s, t, h, d, dtype=jnp.float32):
    return (
        jnp.asarray(rng.normal(size=(b, s, h, d)), dtype),
        jnp.asarray(rng.normal(size=(b, t, h, d)), dtype),
        jnp.asarray(rng.normal(size=(b, t, h, d)), dtype),
    )


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("t,block", [(64, 16), (60, 16), (33, 64)])
def test_blockwise_matches_dense(causal, t, block):
    rng = np.random.default_rng(0)
    q, k, v = _qkv(rng, 2, t, t, 3, 8)
    want = dot_product_attention(q, k, v, causal=causal, dtype=jnp.float32, impl="dense")
    got = blockwise_attention(q, k, v, causal=causal, dtype=jnp.float32, block_kv=block)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_blockwise_cross_attention_rectangular():
    rng = np.random.default_rng(1)
    q, k, v = _qkv(rng, 2, 7, 45, 2, 8)
    want = dot_product_attention(q, k, v, dtype=jnp.float32, impl="dense")
    got = blockwise_attention(q, k, v, dtype=jnp.float32, block_kv=16)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_blockwise_causal_suffix_queries():
    # s < t with causal: queries are the LAST s positions (decode-style)
    rng = np.random.default_rng(2)
    q, k, v = _qkv(rng, 1, 5, 32, 2, 8)
    want = dot_product_attention(q, k, v, causal=True, dtype=jnp.float32, impl="dense")
    got = blockwise_attention(q, k, v, causal=True, dtype=jnp.float32, block_kv=8)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_blockwise_padding_bias():
    # BERT-style (B, 1, 1, T) padding bias
    rng = np.random.default_rng(3)
    q, k, v = _qkv(rng, 2, 24, 24, 2, 8)
    mask = (rng.random((2, 24)) > 0.3).astype(np.float32)
    bias = jnp.where(jnp.asarray(mask)[:, None, None, :] > 0, 0.0, -1e30)
    want = dot_product_attention(q, k, v, bias=bias, dtype=jnp.float32, impl="dense")
    got = blockwise_attention(q, k, v, bias=bias, dtype=jnp.float32, block_kv=8)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_auto_dispatch_threshold():
    rng = np.random.default_rng(4)
    q, k, v = _qkv(rng, 1, 1024, 1024, 1, 8, jnp.bfloat16)
    # auto at seq 1024 must agree with the explicit blockwise path bit-for-bit
    auto = dot_product_attention(q, k, v, causal=True)
    blk = dot_product_attention(q, k, v, causal=True, impl="blockwise")
    np.testing.assert_array_equal(np.asarray(auto), np.asarray(blk))


def _temp_bytes(fn, *args):
    c = jax.jit(fn).lower(*args).compile()
    try:
        return c.memory_analysis().temp_size_in_bytes
    except (AttributeError, NotImplementedError):
        pytest.skip("memory_analysis unsupported on this backend")


def test_blockwise_memory_vs_dense_forward():
    """Dense forward peak temp memory carries the full (B, H, S, S) f32
    score matrix; blockwise must not."""
    b, s, h, d = 1, 2048, 4, 16
    q = jnp.zeros((b, s, h, d), jnp.bfloat16)
    dense_tmp = _temp_bytes(
        lambda q: dot_product_attention(q, q, q, causal=True, impl="dense"), q
    )
    blk_tmp = _temp_bytes(
        lambda q: dot_product_attention(q, q, q, causal=True, impl="blockwise"), q
    )
    score_bytes = b * h * s * s * 4
    assert dense_tmp >= score_bytes  # sanity: dense really pays S^2
    # blockwise must beat the score matrix and stay well under dense peak
    # (measured here: ~35 MB vs dense ~136 MB at S=2048)
    assert blk_tmp < score_bytes, (dense_tmp, blk_tmp)
    assert blk_tmp < dense_tmp / 2, (dense_tmp, blk_tmp)


def test_blockwise_memory_vs_dense_backward():
    """The TRAINING memory bound is what matters: without remat on the
    scan step, grad-of-blockwise stores per-block probs residuals summing
    to the same O(S*T) the dense path pays."""
    b, s, h, d = 1, 2048, 4, 16
    q = jnp.zeros((b, s, h, d), jnp.bfloat16)

    def loss(impl):
        def f(q):
            return jnp.sum(
                jnp.asarray(
                    dot_product_attention(q, q, q, causal=True, impl=impl),
                    jnp.float32,
                )
            )

        return f

    dense_tmp = _temp_bytes(jax.grad(loss("dense")), q)
    blk_tmp = _temp_bytes(jax.grad(loss("blockwise")), q)
    score_bytes = b * h * s * s * 4
    assert dense_tmp >= score_bytes
    assert blk_tmp < dense_tmp / 2, (dense_tmp, blk_tmp)


def test_gpt2_fullseq_forward_uses_blockwise_without_oom():
    """Full-scale GPT-2 seq length through the model path (layers=1 to
    keep runtime sane; the attention shape is what matters)."""
    from consensusml_tpu.models.gpt2 import GPT2Config, GPT2LM

    model = GPT2LM(
        config=GPT2Config(
            vocab_size=128, hidden=64, layers=1, heads=4, max_len=1024, dropout=0.0
        )
    )
    ids = jnp.zeros((1, 1024), jnp.int32)
    params = model.init(jax.random.key(0), ids)["params"]
    logits = model.apply({"params": params}, ids, deterministic=True)
    assert logits.shape == (1, 1024, 128)
