"""Extended codec suite: random-k, QSGD, sign, PowerSGD + filtered CHOCO.

Oracles: round-trip shape/dtype, unbiasedness (Monte Carlo over rng draws)
for the unbiased codecs, wire-size accounting, backend cross-agreement for
stochastic compressed gossip, and LoRA-style filtered compression.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from consensusml_tpu.comm import WorkerMesh
from consensusml_tpu.compress import (
    PowerSGDCompressor,
    QSGDCompressor,
    RandomKCompressor,
    SignCompressor,
)
from consensusml_tpu.consensus import GossipConfig
from consensusml_tpu.data import SyntheticClassification, round_batches
from consensusml_tpu.models import MLP, mlp_loss_fn
from consensusml_tpu.topology import RingTopology
from consensusml_tpu.train import (
    LocalSGDConfig,
    init_stacked_state,
    make_collective_train_step,
    make_simulated_train_step,
)


@pytest.fixture
def x():
    return jnp.asarray(np.random.default_rng(0).normal(size=(33, 17)), jnp.float32)


# ---------------------------------------------------------------------------
# round-trip + statistical properties
# ---------------------------------------------------------------------------


def test_randomk_roundtrip_and_unbiased(x):
    comp = RandomKCompressor(ratio=0.25, unbiased=True)
    acc = jnp.zeros_like(x)
    n_draws = 300
    for i in range(n_draws):
        y = comp.decompress(comp.compress(x, rng=jax.random.key(i)))
        assert y.shape == x.shape and y.dtype == x.dtype
        acc = acc + y
    # E[dec(comp(x))] = x (coordinates scaled by n/k). Per-coordinate
    # variance of one draw is x^2 (n/k - 1) = 3 x^2, so the Monte Carlo
    # mean's sigma is |x| sqrt(3/n_draws); allow 4.5 sigma + float slack.
    sigma = np.abs(np.asarray(x)) * np.sqrt(3.0 / n_draws)
    err = np.abs(np.asarray(acc / n_draws) - np.asarray(x))
    assert (err <= 4.5 * sigma + 1e-3).all(), f"bias beyond 4.5 sigma: {err.max()}"


def test_qsgd_roundtrip_and_unbiased(x):
    comp = QSGDCompressor(chunk=64)
    acc = jnp.zeros_like(x)
    n_draws = 300
    for i in range(n_draws):
        y = comp.decompress(comp.compress(x, rng=jax.random.key(i)))
        assert y.shape == x.shape and y.dtype == x.dtype
        # quantization error bounded by one level
        assert float(jnp.max(jnp.abs(y - x))) <= float(jnp.max(jnp.abs(x))) / 127 + 1e-6
        acc = acc + y
    np.testing.assert_allclose(np.asarray(acc / n_draws), np.asarray(x), atol=0.01)


def test_sign_roundtrip(x):
    comp = SignCompressor(chunk=64)
    p = comp.compress(x)
    y = comp.decompress(p)
    assert y.shape == x.shape and y.dtype == x.dtype
    # decoded signs match input signs, magnitude is per-chunk mean |x|
    np.testing.assert_array_equal(
        np.sign(np.asarray(y)).ravel(), np.where(np.asarray(x).ravel() >= 0, 1, -1)
    )
    # 1 bit/elem + scales: payload must be ~32x smaller than f32
    wire = comp.wire_bytes(x.shape, jnp.float32)
    assert wire < x.size * 4 / 6


def test_powersgd_roundtrip_and_rank(x):
    comp = PowerSGDCompressor(rank=4)
    p = comp.compress(x)
    y = comp.decompress(p)
    assert y.shape == x.shape and y.dtype == x.dtype
    assert np.linalg.matrix_rank(np.asarray(y)) <= 4
    # a rank-2 matrix is reconstructed (nearly) exactly at rank >= 2
    rng = np.random.default_rng(1)
    lowrank = jnp.asarray(
        rng.normal(size=(30, 2)) @ rng.normal(size=(2, 20)), jnp.float32
    )
    y2 = comp.decompress(comp.compress(lowrank))
    np.testing.assert_allclose(np.asarray(y2), np.asarray(lowrank), atol=1e-3)
    # 1-D leaves pass through exactly
    v = jnp.arange(7.0)
    assert comp.decompress(comp.compress(v)) is v


def test_stochastic_compress_tree_requires_rng(x):
    with pytest.raises(ValueError, match="rng"):
        RandomKCompressor(ratio=0.5).compress_tree({"a": x})


# ---------------------------------------------------------------------------
# end-to-end gossip with the new codecs
# ---------------------------------------------------------------------------


def _train(compressor, rounds=30, world=4, gamma=0.4):
    topo = RingTopology(world)
    model = MLP(hidden=16)
    cfg = LocalSGDConfig(
        gossip=GossipConfig(topology=topo, compressor=compressor, gamma=gamma),
        optimizer=optax.adam(2e-3),
        h=1,
    )
    init = lambda rng: model.init(rng, jnp.zeros((1, 28, 28, 1)))["params"]
    data = SyntheticClassification(n=1024)
    step = make_simulated_train_step(cfg, mlp_loss_fn(model))
    state = init_stacked_state(cfg, init, jax.random.key(0), world)
    losses = []
    for batch in round_batches(data, world, h=1, batch=32, rounds=rounds):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    return losses, state


@pytest.mark.parametrize(
    "comp",
    [
        RandomKCompressor(ratio=0.25),
        QSGDCompressor(chunk=128),
        SignCompressor(chunk=128),
        PowerSGDCompressor(rank=2),
    ],
    ids=["randomk", "qsgd", "sign", "powersgd"],
)
def test_choco_converges_with_codec(comp):
    losses, _ = _train(comp)
    assert losses[-1] < 0.6 * losses[0], f"no convergence: {losses[0]} -> {losses[-1]}"
    assert all(np.isfinite(losses))


def test_stochastic_codec_backends_agree():
    """Random-k gossip must produce identical trajectories on the collective
    and simulated backends (same per-worker rng -> same random indices)."""
    topo = RingTopology(4)
    model = MLP(hidden=16)
    cfg = LocalSGDConfig(
        gossip=GossipConfig(
            topology=topo, compressor=RandomKCompressor(ratio=0.5), gamma=0.5
        ),
        optimizer=optax.sgd(0.05, momentum=0.9),
        h=2,
    )
    init = lambda rng: model.init(rng, jnp.zeros((1, 28, 28, 1)))["params"]
    data = SyntheticClassification(n=256)
    wmesh = WorkerMesh.create(topo, devices=jax.devices()[:4])
    step_c = make_collective_train_step(cfg, mlp_loss_fn(model), wmesh)
    step_s = make_simulated_train_step(cfg, mlp_loss_fn(model))
    state_c = wmesh.shard_stacked(init_stacked_state(cfg, init, jax.random.key(0), 4))
    state_s = init_stacked_state(cfg, init, jax.random.key(0), 4)
    for batch in round_batches(data, 4, h=2, batch=16, rounds=3):
        state_c, m_c = step_c(state_c, wmesh.shard_stacked(batch))
        state_s, m_s = step_s(state_s, batch)
    for a, b in zip(jax.tree.leaves(state_c.params), jax.tree.leaves(state_s.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# filtered compression (LoRA pattern)
# ---------------------------------------------------------------------------


def test_filtered_compressed_gossip():
    """Compressor + path_filter: only adapter-like leaves are gossiped
    (compressed), frozen leaves stay bit-identical, and CHOCO state covers
    only the filtered leaves."""
    topo = RingTopology(4)
    flt = lambda path: any(getattr(k, "key", None) == "adapter" for k in path)
    cfg_g = GossipConfig(
        topology=topo, compressor=QSGDCompressor(chunk=64), gamma=0.6, path_filter=flt
    )
    from consensusml_tpu.consensus import ConsensusEngine

    engine = ConsensusEngine(cfg_g)
    rng = np.random.default_rng(0)
    params = {
        "adapter": jnp.asarray(rng.normal(size=(4, 8, 8)), jnp.float32),
        "frozen": jnp.asarray(rng.normal(size=(4, 8, 8)), jnp.float32),
    }
    # stacked params: bucketed CHOCO buffers need the worker count
    state = engine.init_state(params, world_size=4)
    assert len(jax.tree.leaves(state.xhat)) == 1  # adapters only (1 bucket)

    w = jnp.asarray(topo.mixing_matrix(), jnp.float32)
    keys = jax.random.split(jax.random.key(7), 4)
    mixed, state = engine.round_simulated(params, state, w, rng=keys)
    np.testing.assert_array_equal(
        np.asarray(mixed["frozen"]), np.asarray(params["frozen"])
    )
    assert not np.allclose(np.asarray(mixed["adapter"]), np.asarray(params["adapter"]))

    # repeated rounds contract adapter disagreement
    disagreement = lambda t: float(
        jnp.sqrt(jnp.mean(jnp.sum((t - jnp.mean(t, 0, keepdims=True)) ** 2, (1, 2))))
    )
    d0 = disagreement(params["adapter"])
    cur = mixed
    for i in range(20):
        keys = jax.vmap(jax.random.fold_in, in_axes=(0, None))(keys, i)
        cur, state = engine.round_simulated(cur, state, w, rng=keys)
    assert disagreement(cur["adapter"]) < 0.2 * d0
