"""Parity tests for the fused LayerNorm kernel (models/fused_ln.py).

Same protocol as test_fused_bn.py: the jnp path and the Pallas kernels
in interpreter mode are pinned against flax ``nn.LayerNorm`` — values
AND gradients through the row statistics. The compiled-kernel path is
exercised on real hardware by the perf tooling (tools/lm_sweep.py
--norm); interpreter mode does not model Mosaic alignment, which is why
shapes here mirror the real configs (hidden a multiple of 128).
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from consensusml_tpu.models.fused_ln import FusedLayerNorm, fused_layer_norm
from consensusml_tpu.models.gpt2 import GPT2Config, GPT2LM


def _ref_ln(x, gamma, beta, eps=1e-6):
    mod = nn.LayerNorm(epsilon=eps, dtype=jnp.float32)
    return mod.apply({"params": {"scale": gamma, "bias": beta}}, x)


@pytest.mark.parametrize("impl", ["jnp", "interpret"])
@pytest.mark.parametrize("shape,dtype", [
    ((4, 32, 256), jnp.bfloat16),   # bert-ish
    ((2, 16, 128), jnp.float32),
    ((8, 1024), jnp.bfloat16),      # pre-flattened rows
])
def test_forward_matches_flax(impl, shape, dtype):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=shape) * 3 + 1, dtype)
    h = shape[-1]
    gamma = jnp.asarray(rng.normal(size=(h,)) * 0.5 + 1, jnp.float32)
    beta = jnp.asarray(rng.normal(size=(h,)), jnp.float32)
    got = fused_layer_norm(x, gamma, beta, 1e-6, jnp.float32, impl)
    want = _ref_ln(x, gamma, beta)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
    )


@pytest.mark.parametrize("impl", ["jnp", "interpret"])
def test_gradients_match_flax(impl):
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(4, 8, 256)), jnp.float32)
    gamma = jnp.asarray(rng.normal(size=(256,)) * 0.5 + 1, jnp.float32)
    beta = jnp.asarray(rng.normal(size=(256,)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(4, 8, 256)), jnp.float32)

    def loss_fused(x, g, b):
        return jnp.sum(fused_layer_norm(x, g, b, 1e-6, jnp.float32, impl) * w)

    def loss_ref(x, g, b):
        return jnp.sum(_ref_ln(x, g, b) * w)

    got = jax.grad(loss_fused, argnums=(0, 1, 2))(x, gamma, beta)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(x, gamma, beta)
    for a, b_ in zip(got, want):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), atol=3e-4, rtol=3e-4
        )


def test_bf16_out_equals_f32_out_then_cast():
    """out_dtype=bf16 must be exactly "f32 LN then cast" — the invariant
    that lets the GPT-2 blocks feed the kernel straight into a bf16
    matmul."""
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(4, 16, 256)), jnp.bfloat16)
    gamma = jnp.ones((256,), jnp.float32)
    beta = jnp.zeros((256,), jnp.float32)
    a = fused_layer_norm(x, gamma, beta, 1e-6, jnp.bfloat16, "jnp")
    b = fused_layer_norm(x, gamma, beta, 1e-6, jnp.float32, "jnp").astype(
        jnp.bfloat16
    )
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_odd_hidden_falls_back():
    """H not a lane multiple routes to the jnp path (same math), never
    a Pallas error."""
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(6, 100)), jnp.float32)
    gamma = jnp.ones((100,), jnp.float32)
    beta = jnp.zeros((100,), jnp.float32)
    got = fused_layer_norm(x, gamma, beta, 1e-6, jnp.float32, "pallas")
    want = _ref_ln(x, gamma, beta)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5
    )


def test_module_param_names_match_flax():
    """FusedLayerNorm uses flax's scale/bias names so checkpoints and
    gossip path filters are impl-agnostic."""
    mod = FusedLayerNorm(impl="jnp")
    params = mod.init(jax.random.key(0), jnp.zeros((2, 128)))["params"]
    assert set(params) == {"scale", "bias"}


def test_gpt2_norm_impl_parity():
    """A small GPT-2 forward with norm_impl="interpret" matches the
    default flax-LN model on the same params (the kernels are a
    numerics-preserving swap, modulo bf16 rounding at the LN output)."""
    cfg = dict(
        vocab_size=64, hidden=128, layers=2, heads=4, max_len=32, dropout=0.0
    )
    m_flax = GPT2LM(config=GPT2Config(**cfg))
    m_fused = GPT2LM(config=GPT2Config(norm_impl="interpret", **cfg))
    ids = jnp.asarray(
        np.random.default_rng(4).integers(0, 64, size=(2, 16)), jnp.int32
    )
    params = m_flax.init(jax.random.key(0), ids)["params"]
    a = m_flax.apply({"params": params}, ids)
    b = m_fused.apply({"params": params}, ids)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=0.05, rtol=0.05)
