"""End-to-end local-SGD tests — the reference's config 1 and the
collective/simulated cross-validation (SURVEY.md §7 steps 3-4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from consensusml_tpu.comm import WorkerMesh
from consensusml_tpu.compress import TopKCompressor
from consensusml_tpu.consensus import GossipConfig
from consensusml_tpu.data import SyntheticClassification, round_batches
from consensusml_tpu.models import MLP, mlp_loss_fn
from consensusml_tpu.topology import DenseTopology, RingTopology
from consensusml_tpu.train import (
    LocalSGDConfig,
    init_stacked_state,
    make_collective_train_step,
    make_simulated_train_step,
)


def _mlp_setup(topo, h=2, lr=1e-2, compressor=None, gamma=1.0, hidden=32):
    model = MLP(hidden=hidden)
    cfg = LocalSGDConfig(
        gossip=GossipConfig(topology=topo, compressor=compressor, gamma=gamma),
        optimizer=optax.adam(lr),
        h=h,
    )
    init = lambda rng: model.init(rng, jnp.zeros((1, 28, 28, 1)))["params"]
    return model, cfg, init


def test_config1_mlp_dense_4workers_end_to_end():
    """BASELINE.json configs[0]: MLP 'MNIST', 4 simulated workers, dense
    gossip, CPU. Loss must fall, accuracy must rise, and dense gossip must
    keep consensus error at ~0 (exact averaging every round)."""
    topo = DenseTopology(4)
    model, cfg, init = _mlp_setup(topo)
    data = SyntheticClassification(n=4096)
    step = make_simulated_train_step(cfg, mlp_loss_fn(model))
    state = init_stacked_state(cfg, init, jax.random.key(0), topo.world_size)

    losses, errs = [], []
    for batch in round_batches(data, topo.world_size, h=cfg.h, batch=64, rounds=50):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
        errs.append(float(metrics["consensus_error"]))

    assert losses[-1] < 0.3 * losses[0], f"loss did not fall: {losses[0]} -> {losses[-1]}"
    assert errs[-1] < 1e-3, f"dense gossip should reach exact consensus, err={errs[-1]}"

    # accuracy on held-out-ish data with worker-0 params
    params0 = jax.tree.map(lambda x: x[0], state.params)
    ev = data.eval_batch(512)
    preds = jnp.argmax(model.apply({"params": params0}, ev["image"]), -1)
    acc = float(jnp.mean((preds == ev["label"]).astype(jnp.float32)))
    assert acc > 0.9, f"accuracy {acc}"


def test_collective_matches_simulated_trajectory():
    """Same seeds, same data => the shard_map/ppermute backend and the
    mixing-matrix backend produce the same training trajectory."""
    topo = RingTopology(4)
    model, cfg, init = _mlp_setup(topo, h=2, hidden=16)
    data = SyntheticClassification(n=1024)
    loss_fn = mlp_loss_fn(model)

    sim_step = make_simulated_train_step(cfg, loss_fn)
    wmesh = WorkerMesh.create(topo, platform="cpu")
    col_step = make_collective_train_step(cfg, loss_fn, wmesh)

    state = init_stacked_state(cfg, init, jax.random.key(1), topo.world_size)
    sim_state = state
    col_state = wmesh.shard_stacked(state)

    sim_metrics, col_metrics = None, None
    for batch in round_batches(data, topo.world_size, h=cfg.h, batch=32, rounds=5):
        sim_state, sim_metrics = sim_step(sim_state, batch)
        col_state, col_metrics = col_step(col_state, batch)

    assert float(sim_metrics["loss"]) == pytest.approx(
        float(col_metrics["loss"]), rel=1e-4
    )
    assert float(sim_metrics["consensus_error"]) == pytest.approx(
        float(col_metrics["consensus_error"]), rel=1e-3, abs=1e-5
    )
    for a, b in zip(jax.tree.leaves(sim_state.params), jax.tree.leaves(col_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5)


def test_torus_collective_trajectory():
    """Multi-axis (torus) topology through the collective backend with the
    SAME flat-stacked inputs as the simulated backend — the two must agree
    (BASELINE.json configs[3] is torus gossip)."""
    from consensusml_tpu.topology import TorusTopology

    topo = TorusTopology(2, 4)
    model, cfg, init = _mlp_setup(topo, h=1, hidden=16)
    data = SyntheticClassification(n=1024)
    loss_fn = mlp_loss_fn(model)

    sim_step = make_simulated_train_step(cfg, loss_fn)
    wmesh = WorkerMesh.create(topo, platform="cpu")
    col_step = make_collective_train_step(cfg, loss_fn, wmesh)

    state = init_stacked_state(cfg, init, jax.random.key(9), topo.world_size)
    sim_state, col_state = state, wmesh.shard_stacked(state)
    for batch in round_batches(data, topo.world_size, h=1, batch=16, rounds=3):
        sim_state, sm = sim_step(sim_state, batch)
        col_state, cm = col_step(col_state, batch)
    assert float(sm["loss"]) == pytest.approx(float(cm["loss"]), rel=1e-4)
    for a, b in zip(jax.tree.leaves(sim_state.params), jax.tree.leaves(col_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5)


def test_int8_small_leaf_wire_stays_small():
    """Regression: int8 chunking must not balloon small tensors (e.g. the
    k values of a top-k payload) to a full zero-padded chunk."""
    from consensusml_tpu.compress import Int8Compressor

    wire = Int8Compressor(chunk=256).wire_bytes((10,), jnp.float32)
    assert wire == 10 + 4  # 10 int8 + one f32 scale — not 256 + 4


def test_local_sgd_h_steps_reduce_comm_rounds():
    """H=4 inner steps: one gossip round per 4 optimizer steps, still
    converges (BASELINE.json configs[2] pattern, small scale)."""
    topo = RingTopology(4)
    model, cfg, init = _mlp_setup(topo, h=4, lr=5e-3)
    data = SyntheticClassification(n=2048)
    step = make_simulated_train_step(cfg, mlp_loss_fn(model))
    state = init_stacked_state(cfg, init, jax.random.key(2), topo.world_size)
    losses = []
    errs = []
    for batch in round_batches(data, topo.world_size, h=4, batch=32, rounds=40, seed=1):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
        errs.append(float(m["consensus_error"]))
    assert losses[-1] < 0.5 * losses[0]
    # ring gossip doesn't zero the error, but it must stay bounded and
    # far below the scale of the initial random-init disagreement
    assert errs[-1] < errs[0]


def test_compressed_local_sgd_converges():
    """Top-k compressed gossip (CHOCO) still trains."""
    topo = RingTopology(4)
    model, cfg, init = _mlp_setup(
        topo, h=2, compressor=TopKCompressor(ratio=0.25), gamma=0.5
    )
    data = SyntheticClassification(n=2048)
    step = make_simulated_train_step(cfg, mlp_loss_fn(model))
    state = init_stacked_state(cfg, init, jax.random.key(3), topo.world_size)
    losses = []
    for batch in round_batches(data, topo.world_size, h=2, batch=32, rounds=40, seed=2):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < 0.5 * losses[0]
