"""Fused gossip wire (ISSUE 9): one-pass pack+quantize kernels, the
fp8/e4m3 codec, first-class bucket-aligned sub-byte codecs, and
pipelined multi-round overlap gossip.

The fused wire is a TRANSPORT fusion, not a codec change — its whole
contract is "same bytes, same bits, fewer HBM round-trips", so nearly
every test here is a bit-exactness pin: fused payloads vs the two-step
codec's, fused engine rounds vs unfused, kernel (interpret) impl vs jnp,
collective vs simulated. The pipelined-overlap tests pin the ISSUE's
acceptance pair: depth 1 bit-exact with the plain overlap recurrence,
depth > 1 converging to the same consensus mean.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from consensusml_tpu.comm import WorkerMesh, simulated
from consensusml_tpu.compress import (
    Fp8Compressor,
    PallasFp8Compressor,
    PallasInt4Compressor,
    PallasInt8Compressor,
    fused_bucket_codec,
    resolve_codec_impl,
    topk_int8_compressor,
)
# the one shard_map-with-replication-check-off shim (pallas_call has no
# replication rule); shared with the fused-wire jaxpr contract
from consensusml_tpu.analysis.jaxpr_contracts import _shard_map_no_check
from consensusml_tpu.compress.kernels import FusedBucketCodec
from consensusml_tpu.consensus import (
    ConsensusEngine,
    GossipConfig,
    OverlapState,
)
from consensusml_tpu.consensus.bucketing import build_fused_plan
from consensusml_tpu.topology import RingTopology

WORLD = 8
TOPO = RingTopology(WORLD)

# chunk 128 = the kernel lane width: valid for every impl of every codec
CODECS = {
    "int8": PallasInt8Compressor,
    "int4": PallasInt4Compressor,
    "fp8": PallasFp8Compressor,
}


def _tree(seed=0, world=None):
    """Odd leaf sizes (bucket padding) + one sub-chunk leaf."""
    rng = np.random.default_rng(seed)
    lead = () if world is None else (world,)
    return {
        "w": jnp.asarray(rng.normal(size=lead + (300, 17)), jnp.float32),
        "b": jnp.asarray(rng.normal(size=lead + (513,)), jnp.float32),
    }


def _eq(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# fp8 codec
# ---------------------------------------------------------------------------


def test_fp8_reference_roundtrip_properties():
    """e4m3's relative-precision profile: per-chunk max lands exactly on
    the format max, small values keep ~2 significant bits, zero chunks
    decode to exact zeros."""
    comp = Fp8Compressor(chunk=128)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(5, 128)), jnp.float32)
    out = comp.decompress(comp.compress(x))
    assert out.shape == x.shape and out.dtype == x.dtype
    # e4m3 keeps 3 mantissa bits: relative error <= 2^-4 on the bulk
    err = np.abs(np.asarray(out) - np.asarray(x))
    assert np.all(err <= np.abs(np.asarray(x)) * 0.0625 + 1e-6)
    zeros = jnp.zeros((256,), jnp.float32)
    np.testing.assert_array_equal(
        np.asarray(comp.decompress(comp.compress(zeros))), np.zeros((256,))
    )


def test_pallas_fp8_interpret_matches_reference():
    comp_i = PallasFp8Compressor(chunk=128, impl="interpret")
    comp_r = Fp8Compressor(chunk=128)
    x = jnp.asarray(
        np.random.default_rng(1).normal(size=(1024,)), jnp.float32
    )
    pi, pr = comp_i.compress(x), comp_r.compress(x)
    # payload bits agree modulo the jit-vs-eager 1-ulp scale difference
    # (XLA folds /448 to a reciprocal multiply under jit); the decoded
    # values are what the wire contract is about
    np.testing.assert_allclose(
        np.asarray(pi.scales), np.asarray(pr.scales), rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(comp_i.decompress(pi)),
        np.asarray(comp_r.decompress(pr)),
        rtol=1e-5, atol=1e-6,
    )


def test_fp8_advertises_bucket_alignment_and_fused_wire():
    for comp in (Fp8Compressor(chunk=256), PallasFp8Compressor(chunk=256)):
        assert comp.bucket_alignment() == 256
        assert comp.fused_wire() == "fp8"


# ---------------------------------------------------------------------------
# fused codec: payload/bit parity with the two-step path
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("fmt", sorted(CODECS))
def test_fused_encode_payload_is_bit_identical_to_codec(fmt):
    """fused encode == compress(x - xhat) + the xhat tracking update,
    payload bits INCLUDED — the wire ships identical bytes."""
    comp = CODECS[fmt](chunk=128, impl="jnp")
    codec = fused_bucket_codec(comp)
    assert isinstance(codec, FusedBucketCodec) and codec.fmt == fmt
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(2048,)), jnp.float32)
    h = jnp.asarray(0.3 * rng.normal(size=(2048,)), jnp.float32)
    payload, new_hat = codec.encode(x, h)
    want = comp.compress(x - h)
    np.testing.assert_array_equal(
        np.asarray(payload.data), np.asarray(want.data)
    )
    np.testing.assert_array_equal(
        np.asarray(payload.scales), np.asarray(want.scales)
    )
    np.testing.assert_array_equal(
        np.asarray(new_hat), np.asarray(h + comp.decompress(want))
    )


@pytest.mark.parametrize("fmt", sorted(CODECS))
def test_fused_decode_accumulate_matches_two_step_chain(fmt):
    """fused receive == self-weight multiply + per-neighbor
    decompress_accumulate, in the SAME float-addition order."""
    comp = CODECS[fmt](chunk=128, impl="jnp")
    codec = fused_bucket_codec(comp)
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(1024,)), jnp.float32)
    s = jnp.asarray(rng.normal(size=(1024,)), jnp.float32)
    q = comp.compress(x)
    weights = (TOPO.self_weight,) + tuple(sh.weight for sh in TOPO.shifts)
    got = codec.decode_accumulate(s, [q] * len(weights), weights)
    recv = weights[0] * comp.decompress(q)
    for w in weights[1:]:
        recv = comp.decompress_accumulate(q, recv, w)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(s + recv))


def test_fused_codec_interpret_matches_jnp_impl():
    """The pallas-interpreter kernels and the jnp reference share one
    quantization definition (_fused_quant) — identical payload bits and
    identical accumulate, both jitted."""
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.normal(size=(2048,)), jnp.float32)
    h = jnp.asarray(0.3 * rng.normal(size=(2048,)), jnp.float32)
    s = jnp.asarray(rng.normal(size=(2048,)), jnp.float32)
    for fmt in sorted(CODECS):
        cj = FusedBucketCodec(fmt=fmt, chunk=128, impl="jnp")
        ci = FusedBucketCodec(fmt=fmt, chunk=128, impl="interpret")
        # jit both so XLA's constant-division folding applies equally
        pj, hj = jax.jit(cj.encode)(x, h)
        pi, hi = jax.jit(ci.encode)(x, h)
        np.testing.assert_array_equal(np.asarray(pj.data), np.asarray(pi.data))
        np.testing.assert_array_equal(
            np.asarray(pj.scales), np.asarray(pi.scales)
        )
        np.testing.assert_array_equal(np.asarray(hj), np.asarray(hi))
        aj = jax.jit(
            lambda s, p: cj.decode_accumulate(s, [p, p], (0.5, 0.25))
        )(s, pj)
        ai = jax.jit(
            lambda s, p: ci.decode_accumulate(s, [p, p], (0.5, 0.25))
        )(s, pi)
        np.testing.assert_array_equal(np.asarray(aj), np.asarray(ai))


# ---------------------------------------------------------------------------
# gating: which codecs ride the fused wire
# ---------------------------------------------------------------------------


def test_fused_bucket_codec_gating():
    # composed sparse codec: no fused_wire() tag -> two-step path
    assert fused_bucket_codec(topk_int8_compressor(ratio=0.1, chunk=128)) is None
    # per-chunk quantizers fuse, with the codec's own alignment
    codec = fused_bucket_codec(PallasInt8Compressor(chunk=512))
    assert codec is not None and codec.chunk == 512
    # jnp impl accepts any even alignment; kernel impls need lane multiples
    assert fused_bucket_codec(PallasInt4Compressor(chunk=128, impl="interpret")) is not None


def test_fused_wire_config_validation():
    comp = PallasInt8Compressor(chunk=128, impl="jnp")
    with pytest.raises(ValueError):
        GossipConfig(topology=TOPO, compressor=comp, gamma=0.5, fused_wire="yes")
    with pytest.raises(NotImplementedError):
        GossipConfig(topology=TOPO, fused_wire=True)  # nothing to fuse
    with pytest.raises(NotImplementedError):  # per-leaf wire: no buckets
        GossipConfig(
            topology=TOPO, compressor=comp, gamma=0.5, fused_wire=True,
            bucket_bytes=None,
        )
    with pytest.raises(NotImplementedError):  # codec has no fused kernels
        GossipConfig(
            topology=TOPO, compressor=topk_int8_compressor(ratio=0.1),
            gamma=0.5, fused_wire=True,
        )
    # auto: engages for fused-capable codecs, silently two-step otherwise
    assert ConsensusEngine(
        GossipConfig(topology=TOPO, compressor=comp, gamma=0.5)
    ).fused_wire_active
    assert not ConsensusEngine(
        GossipConfig(
            topology=TOPO, compressor=comp, gamma=0.5, fused_wire=False
        )
    ).fused_wire_active
    assert not ConsensusEngine(
        GossipConfig(
            topology=TOPO, compressor=topk_int8_compressor(ratio=0.1),
            gamma=0.5,
        )
    ).fused_wire_active


def test_resolve_codec_impl():
    # this box has no TPU: "auto" must pick the interpreter (the kernel
    # CODE path), never silently the jnp reference
    assert resolve_codec_impl() in ("pallas", "interpret")
    if jax.default_backend() != "tpu":
        assert resolve_codec_impl() == "interpret"
    assert resolve_codec_impl("jnp") == "jnp"
    assert resolve_codec_impl("pallas") == "pallas"


# ---------------------------------------------------------------------------
# engine rounds: fused wire == two-step path, both backends
# ---------------------------------------------------------------------------


def _engines(fmt: str, impl: str = "jnp"):
    comp = CODECS[fmt](chunk=128, impl=impl)
    mk = lambda fw: ConsensusEngine(
        GossipConfig(
            topology=TOPO, compressor=comp, gamma=0.5,
            bucket_bytes=16 * 1024, fused_wire=fw,
        )
    )
    return mk("auto"), mk(False)


@pytest.mark.parametrize("fmt", sorted(CODECS))
def test_round_simulated_fused_is_bit_exact_vs_unfused(fmt):
    e_f, e_u = _engines(fmt)
    assert e_f.fused_wire_active and not e_u.fused_wire_active
    w = simulated.mixing_matrix(TOPO)
    tree = _tree(5, WORLD)
    st_f = e_f.init_state(tree, world_size=WORLD)
    st_u = e_u.init_state(tree, world_size=WORLD)
    x_f, x_u = tree, tree
    for _ in range(3):
        x_f, st_f = e_f.round_simulated(x_f, st_f, w)
        x_u, st_u = e_u.round_simulated(x_u, st_u, w)
    _eq(x_f, x_u)
    _eq(st_f.xhat, st_u.xhat)
    _eq(st_f.s, st_u.s)


def test_round_collective_fused_matches_simulated():
    """Cross-backend oracle: the fused collective exchange (payloads on
    the ppermute wire) equals the fused stacked exchange (mixing-matrix
    multiply) — the same cross-validation every other wire has."""
    e_f, _ = _engines("int8")
    wmesh = WorkerMesh.create(TOPO, platform="cpu")

    @jax.jit
    @functools.partial(
        _shard_map_no_check,
        mesh=wmesh.mesh,
        in_specs=P(*TOPO.axis_names),
        out_specs=P(*TOPO.axis_names),
    )
    def run(tree):
        st = e_f.init_state(tree)
        for r in range(2):
            tree, st = e_f.round_collective(tree, st, step=jnp.int32(r))
        return tree

    tree = _tree(6, WORLD)
    got = run(tree)
    w = simulated.mixing_matrix(TOPO)
    want, st = tree, e_f.init_state(tree, world_size=WORLD)
    for _ in range(2):
        want, st = e_f.round_simulated(want, st, w)
    for k in tree:
        np.testing.assert_allclose(
            np.asarray(got[k]), np.asarray(want[k]), rtol=1e-5, atol=1e-6
        )


def test_round_collective_fused_interpret_kernels_run():
    """The pallas-interpreter kernels trace and RUN inside shard_map on
    the CPU mesh (the exact fallback tier-1 depends on), agreeing with
    the unfused two-step round bit-for-bit."""
    e_f, e_u = _engines("int8", impl="interpret")
    assert e_f.fused_wire_active
    wmesh = WorkerMesh.create(TOPO, platform="cpu")

    def mk(engine):
        @jax.jit
        @functools.partial(
            _shard_map_no_check,
            mesh=wmesh.mesh,
            in_specs=P(*TOPO.axis_names),
            out_specs=P(*TOPO.axis_names),
        )
        def run(tree):
            st = engine.init_state(tree)
            tree, _ = engine.round_collective(tree, st, step=jnp.int32(0))
            return tree

        return run

    tree = _tree(7, WORLD)
    _eq(mk(e_f)(tree), mk(e_u)(tree))


def test_overlap_compressed_fused_rides_the_wire():
    """Overlap+compression on the fused wire: the delayed CHOCO
    correction path engages the fused kernels and stays bit-exact with
    the two-step overlap path."""
    comp = PallasInt8Compressor(chunk=128, impl="jnp")
    mk = lambda fw: ConsensusEngine(
        GossipConfig(
            topology=TOPO, compressor=comp, gamma=0.4, overlap=True,
            bucket_bytes=16 * 1024, fused_wire=fw,
        )
    )
    e_f, e_u = mk("auto"), mk(False)
    w = simulated.mixing_matrix(TOPO)
    z_f, z_u = _tree(8, WORLD), _tree(8, WORLD)
    st_f = e_f.init_state(z_f, world_size=WORLD)
    st_u = e_u.init_state(z_u, world_size=WORLD)
    assert isinstance(st_f, OverlapState) and st_f.choco is not None
    for _ in range(4):
        z_f = e_f.apply_correction(z_f, st_f)
        st_f = e_f.correction_simulated(z_f, w, st_f)
        z_u = e_u.apply_correction(z_u, st_u)
        st_u = e_u.correction_simulated(z_u, w, st_u)
    _eq(z_f, z_u)
    _eq(st_f.correction, st_u.correction)


def test_telemetry_reports_fused_wire():
    e_f, e_u = _engines("int8")
    tree = _tree(9)
    t_f, t_u = e_f.telemetry(tree), e_u.telemetry(tree)
    assert t_f["wire_fused_buckets"] == t_f["gossip_buckets"] > 0
    assert t_f["wire_fused_kernel_calls_per_round"] == (
        2 * t_f["gossip_buckets"] * e_f.config.gossip_steps
    )
    assert t_u["wire_fused_buckets"] == 0.0
    # transport fusion: the bytes accounting must not move
    assert (
        t_f["wire_bytes_per_neighbor"] == t_u["wire_bytes_per_neighbor"]
    )
    assert t_f["gossip_pipeline_depth"] == 1.0


# ---------------------------------------------------------------------------
# pipelined multi-round gossip (GossipConfig.pipeline_depth)
# ---------------------------------------------------------------------------


def test_pipeline_config_validation():
    with pytest.raises(ValueError):
        GossipConfig(topology=TOPO, overlap=True, pipeline_depth=0)
    with pytest.raises(NotImplementedError):  # pipelining IS overlap-mode
        GossipConfig(topology=TOPO, pipeline_depth=2)
    eng = ConsensusEngine(
        GossipConfig(topology=TOPO, overlap=True, pipeline_depth=3)
    )
    st = eng.init_state(_tree(0, WORLD), world_size=WORLD)
    assert isinstance(st, OverlapState) and len(st.pending) == 2
    with pytest.raises(ValueError):  # the queue must thread through
        eng.correction_simulated(
            _tree(0, WORLD), simulated.mixing_matrix(TOPO)
        )


def test_pipeline_depth1_is_bit_exact_with_plain_overlap_recurrence():
    """Depth 1 == the pre-pipeline overlap path: correction (W - I) z
    computed this round, applied next round, nothing queued."""
    eng = ConsensusEngine(GossipConfig(topology=TOPO, overlap=True))
    w = simulated.mixing_matrix(TOPO)
    z = _tree(10, WORLD)
    st = eng.init_state(z, world_size=WORLD)
    assert st.pending == ()
    z_ref = z
    corr = jax.tree.map(jnp.zeros_like, z)
    for _ in range(5):
        z = eng.apply_correction(z, st)
        st = eng.correction_simulated(z, w, st)
        # the PR-1 recurrence, spelled out
        z_ref = jax.tree.map(jnp.add, z_ref, corr)
        mixed = eng._mix_exact_tree_simulated(z_ref, w)
        corr = jax.tree.map(
            lambda m, t: (m - t).astype(t.dtype), mixed, z_ref
        )
        _eq(z, z_ref)
        _eq(st.correction, corr)


@pytest.mark.parametrize("depth", [2, 3])
def test_pipeline_exact_overlap_converges_to_same_mean(depth):
    """Pure pipelined gossip drives every worker to the SAME consensus
    mean as depth 1 (the anticipated-correction recurrence stays on
    x <- W x; a naive delayed correction diverges on a ring at D >= 2),
    and every in-flight correction sums to zero across workers."""
    w = simulated.mixing_matrix(TOPO)
    z0 = _tree(11, WORLD)
    mean0 = {k: np.asarray(v).mean(0) for k, v in z0.items()}

    def run(d, rounds=60):
        eng = ConsensusEngine(
            GossipConfig(topology=TOPO, overlap=True, pipeline_depth=d)
        )
        z = z0
        st = eng.init_state(z, world_size=WORLD)
        for _ in range(rounds):
            z = eng.apply_correction(z, st)
            st = eng.correction_simulated(z, w, st)
        return eng, z, st

    eng1, z1, _ = run(1)
    engd, zd, std = run(depth)
    err1 = float(eng1.consensus_error_simulated(z1))
    errd = float(engd.consensus_error_simulated(zd))
    assert errd < 1e-2, f"depth {depth} failed to contract: {errd}"
    assert errd < 10 * max(err1, 1e-6) + 1e-3
    for k in zd:  # same consensus mean as depth 1, within tol
        np.testing.assert_allclose(
            np.asarray(zd[k]).mean(0), mean0[k], atol=1e-4
        )
        np.testing.assert_allclose(
            np.asarray(zd[k]).mean(0), np.asarray(z1[k]).mean(0), atol=1e-4
        )
    # mean-exactness of the queue itself
    for p in std.pending + (std.correction,):
        for leaf in jax.tree.leaves(p):
            np.testing.assert_allclose(
                np.asarray(leaf).sum(0), 0.0, atol=1e-4
            )


def test_pipeline_compressed_overlap_converges_and_preserves_mean():
    """Depth-2 pipelining composes with CHOCO overlap+compression on the
    fused wire: contraction holds and the mean is preserved."""
    comp = PallasInt8Compressor(chunk=128, impl="jnp")
    eng = ConsensusEngine(
        GossipConfig(
            topology=TOPO, overlap=True, compressor=comp, gamma=0.4,
            bucket_bytes=16 * 1024, pipeline_depth=2,
        )
    )
    assert eng.fused_wire_active
    w = simulated.mixing_matrix(TOPO)
    z = _tree(12, WORLD)
    mean0 = {k: np.asarray(v).mean(0) for k, v in z.items()}
    err0 = float(eng.consensus_error_simulated(z))
    st = eng.init_state(z, world_size=WORLD)
    assert len(st.pending) == 1 and st.choco is not None
    for _ in range(60):
        z = eng.apply_correction(z, st)
        st = eng.correction_simulated(z, w, st)
    assert float(eng.consensus_error_simulated(z)) < 0.15 * err0
    for k in z:
        np.testing.assert_allclose(
            np.asarray(z[k]).mean(0), mean0[k], atol=1e-4
        )


def test_pipeline_depth_in_train_step():
    """pipeline_depth > 1 threads through the simulated train step: the
    full local-SGD loop runs and keeps contracting."""
    import optax

    from consensusml_tpu.train import (
        LocalSGDConfig,
        init_stacked_state,
        make_simulated_train_step,
    )

    import flax.linen as nn

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = x.reshape((x.shape[0], -1))
            x = nn.Dense(16)(x)
            return nn.Dense(4)(nn.relu(x))

    model = Tiny()

    def loss_fn(params, model_state, batch, rng):
        logits = model.apply({"params": params}, batch["x"])
        onehot = jax.nn.one_hot(batch["y"], 4)
        return (
            -jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(logits), -1)),
            model_state,
        )

    cfg = LocalSGDConfig(
        gossip=GossipConfig(
            topology=TOPO, overlap=True, pipeline_depth=2
        ),
        optimizer=optax.sgd(0.05),
        h=2,
    )
    step = make_simulated_train_step(cfg, loss_fn)
    init = lambda r: model.init(r, jnp.zeros((1, 8)))["params"]
    state = init_stacked_state(cfg, init, jax.random.key(0), WORLD)
    rngb = np.random.default_rng(13)
    errs = []
    for _ in range(6):
        batch = {
            "x": jnp.asarray(
                rngb.normal(size=(WORLD, cfg.h, 4, 8)), jnp.float32
            ),
            "y": jnp.asarray(
                rngb.integers(0, 4, size=(WORLD, cfg.h, 4)), jnp.int32
            ),
        }
        state, metrics = step(state, batch)
        errs.append(float(metrics["consensus_error"]))
        assert np.isfinite(float(metrics["loss"]))
    assert errs[-1] < errs[0]


def test_build_fused_plan_rejects_mismatched_alignment():
    comp = PallasInt8Compressor(chunk=128, impl="jnp")
    eng = ConsensusEngine(
        GossipConfig(topology=TOPO, compressor=comp, gamma=0.5)
    )
    leaves = jax.tree.leaves(_tree(0))
    plan = eng._codec_plan(leaves)
    assert build_fused_plan(plan, comp) is not None
    with pytest.raises(ValueError):
        build_fused_plan(plan, PallasInt8Compressor(chunk=256, impl="jnp"))
    # codecs without fused kernels yield None, never an error
    assert build_fused_plan(plan, topk_int8_compressor(ratio=0.1)) is None
