"""Property tests for gossip topologies and mixing matrices.

Covers the reference's topology layer (SURVEY.md L2): doubly-stochastic
mixing, symmetry, positive spectral gap, and the consensus contraction
bound ||W x - x_bar|| <= lambda_2 ||x - x_bar||.
"""

import numpy as np
import pytest

from consensusml_tpu.topology import (
    DenseTopology,
    ExponentialTopology,
    OnePeerExponentialTopology,
    RingTopology,
    TorusTopology,
    topology_from_name,
)

TOPOLOGIES = [
    RingTopology(2),
    RingTopology(3),
    RingTopology(8),
    RingTopology(32),
    TorusTopology(2, 2),
    TorusTopology(4, 4),
    TorusTopology(2, 3),
    TorusTopology(1, 8),
    DenseTopology(4),
    DenseTopology(32),
    ExponentialTopology(2),
    ExponentialTopology(6),
    ExponentialTopology(8),
    ExponentialTopology(32),
]


@pytest.mark.parametrize("topo", TOPOLOGIES, ids=lambda t: f"{t.name}{t.mesh_shape}")
def test_doubly_stochastic(topo):
    w = topo.mixing_matrix()
    np.testing.assert_allclose(w.sum(axis=0), 1.0, atol=1e-12)
    np.testing.assert_allclose(w.sum(axis=1), 1.0, atol=1e-12)
    assert (w >= -1e-12).all()


@pytest.mark.parametrize("topo", TOPOLOGIES, ids=lambda t: f"{t.name}{t.mesh_shape}")
def test_symmetric(topo):
    w = topo.mixing_matrix()
    np.testing.assert_allclose(w, w.T, atol=1e-12)


@pytest.mark.parametrize("topo", TOPOLOGIES, ids=lambda t: f"{t.name}{t.mesh_shape}")
def test_spectral_gap_positive(topo):
    # connected + aperiodic (positive self weight) => gap > 0
    assert topo.spectral_gap() > 1e-6


@pytest.mark.parametrize("topo", TOPOLOGIES, ids=lambda t: f"{t.name}{t.mesh_shape}")
def test_consensus_contraction(topo):
    """One gossip round contracts disagreement by at least the spectral gap."""
    rng = np.random.default_rng(0)
    w = topo.mixing_matrix()
    lam2 = 1.0 - topo.spectral_gap()
    for _ in range(5):
        x = rng.normal(size=(topo.world_size, 7))
        xbar = x.mean(axis=0, keepdims=True)
        before = np.linalg.norm(x - xbar)
        after = np.linalg.norm(w @ x - xbar)
        assert after <= lam2 * before + 1e-9
        # mean is preserved exactly by doubly-stochastic mixing
        np.testing.assert_allclose((w @ x).mean(axis=0), xbar[0], atol=1e-12)


def test_dense_one_round_consensus():
    topo = DenseTopology(4)
    w = topo.mixing_matrix()
    np.testing.assert_allclose(w, np.full((4, 4), 0.25), atol=1e-12)
    assert topo.uses_psum


def test_ring_neighbors():
    topo = RingTopology(8)
    assert topo.neighbors(0) == [(1, pytest.approx(1 / 3)), (7, pytest.approx(1 / 3))]
    assert topo.self_weight == pytest.approx(1 / 3)


def test_torus_neighbors_4x4():
    topo = TorusTopology(4, 4)
    # worker at (1,1) = rank 5 hears from (0,1)=1, (2,1)=9, (1,0)=4, (1,2)=6
    assert [r for r, _ in topo.neighbors(5)] == [1, 4, 6, 9]
    for _, wt in topo.neighbors(5):
        assert wt == pytest.approx(1 / 5)


def test_degenerate_sizes():
    assert RingTopology(1).mixing_matrix() == pytest.approx(np.ones((1, 1)))
    np.testing.assert_allclose(
        RingTopology(2).mixing_matrix(), np.full((2, 2), 0.5), atol=1e-12
    )
    # torus with a dimension of 2 merges parallel edges and stays stochastic
    w = TorusTopology(2, 4).mixing_matrix()
    np.testing.assert_allclose(w.sum(axis=1), 1.0, atol=1e-12)


def test_torus_degenerate_matches_ring():
    # a size-2 torus axis merges parallel edges with the TRUE Metropolis
    # weight: torus(1,2) is the same graph (and matrix) as ring(2)
    np.testing.assert_allclose(
        TorusTopology(1, 2).mixing_matrix(), RingTopology(2).mixing_matrix()
    )
    assert TorusTopology(2, 2).spectral_gap() == pytest.approx(2 / 3)


def test_invalid_args():
    with pytest.raises(ValueError):
        RingTopology(0)
    with pytest.raises(ValueError):
        DenseTopology(-1)
    with pytest.raises(ValueError):
        TorusTopology(0, 4)
    with pytest.raises(ValueError):
        topology_from_name("ring", 8, rows=2)  # bogus kwarg
    with pytest.raises(ValueError):
        topology_from_name("torus", 12, rows=5)  # non-divisor
    with pytest.raises(ValueError):
        topology_from_name("torus", 0)
    # single-sided torus spec derives the other dim
    assert topology_from_name("torus", 12, rows=2).mesh_shape == (2, 6)
    assert topology_from_name("torus", 12, cols=2).mesh_shape == (6, 2)


def test_from_name():
    assert topology_from_name("ring", 8).name == "ring"
    assert topology_from_name("dense", 4).uses_psum
    t = topology_from_name("torus", 16)
    assert t.mesh_shape == (4, 4)
    assert topology_from_name("exp", 16).name == "exp"
    assert topology_from_name("onepeer-exp", 16).is_time_varying
    with pytest.raises(ValueError):
        topology_from_name("hypercube", 8)


# ---------------------------------------------------------------------------
# exponential / time-varying topologies
# ---------------------------------------------------------------------------


def test_exp_beats_ring_gap():
    """log-n neighbors buy a far better spectral gap than the ring's."""
    for n in (16, 32, 64):
        assert ExponentialTopology(n).spectral_gap() > 5 * RingTopology(n).spectral_gap()


def test_exp_neighbor_count_logarithmic():
    topo = ExponentialTopology(64)
    # offsets ±{1,2,4,8,16,32} with 32 self-paired -> 11 distinct neighbors
    assert len(topo.neighbors(0)) == 11


@pytest.mark.parametrize("n", [2, 3, 4, 6, 8, 16])
def test_onepeer_phases_doubly_stochastic(n):
    topo = OnePeerExponentialTopology(n)
    for w in topo.phase_matrices():
        np.testing.assert_allclose(w.sum(axis=0), 1.0, atol=1e-12)
        np.testing.assert_allclose(w.sum(axis=1), 1.0, atol=1e-12)
        assert (w >= -1e-12).all()


@pytest.mark.parametrize("n", [2, 4, 8, 16, 32])
def test_onepeer_exact_average_in_log_n_rounds(n):
    """For n = 2^tau one period's product is EXACTLY the uniform average."""
    topo = OnePeerExponentialTopology(n)
    assert topo.period == int(np.log2(n))
    np.testing.assert_allclose(
        topo.effective_matrix(), np.full((n, n), 1.0 / n), atol=1e-12
    )
    assert topo.spectral_gap() == pytest.approx(1.0, abs=1e-9)


def test_onepeer_non_power_of_two_still_contracts():
    topo = OnePeerExponentialTopology(6)
    assert topo.spectral_gap() > 0.3  # per-period contraction


def test_time_varying_guards():
    from consensusml_tpu.topology import TimeVaryingTopology

    with pytest.raises(ValueError):
        TimeVaryingTopology([])
    with pytest.raises(ValueError):
        TimeVaryingTopology([RingTopology(4), RingTopology(8)])
    with pytest.raises(ValueError):
        TimeVaryingTopology([OnePeerExponentialTopology(4)])  # nested TV
    with pytest.raises(ValueError):
        OnePeerExponentialTopology(8).mixing_matrix()  # no single matrix
    # one-worker degenerate case: a single identity phase
    solo = OnePeerExponentialTopology(1)
    np.testing.assert_allclose(solo.effective_matrix(), np.eye(1))
