"""Parity tests: Pallas kernels (interpreter mode) vs jnp reference math.

The Pallas interpreter executes the actual kernel logic (grid, blocks,
stores) on CPU, so these tests verify the kernels' numerics; the TPU
compile path is exercised by bench/graft entry on real hardware.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from consensusml_tpu.compress.kernels import (
    ChunkedTopKCompressor,
    PallasInt8Compressor,
    chunked_topk,
    dequantize_int8,
    quantize_int8,
)
from consensusml_tpu.compress.reference import Int8Compressor


@pytest.mark.parametrize("nchunks,chunk", [(4, 128), (32, 256), (33, 128), (1, 512)])
def test_quantize_kernel_matches_reference(nchunks, chunk):
    rng = np.random.default_rng(0)
    chunks = jnp.asarray(rng.normal(size=(nchunks, chunk)) * 3, jnp.float32)
    q, scales = quantize_int8(chunks, interpret=True)
    ref = Int8Compressor(chunk=chunk).compress(chunks.reshape(-1))
    np.testing.assert_array_equal(np.asarray(q).reshape(-1), np.asarray(ref.data))
    np.testing.assert_allclose(np.asarray(scales), np.asarray(ref.scales), rtol=1e-7)


def test_quantize_kernel_zero_rows():
    chunks = jnp.zeros((8, 128), jnp.float32)
    q, scales = quantize_int8(chunks, interpret=True)
    assert np.all(np.asarray(q) == 0) and np.all(np.asarray(scales) == 0)


def test_dequantize_kernel_roundtrip():
    rng = np.random.default_rng(1)
    chunks = jnp.asarray(rng.normal(size=(16, 256)), jnp.float32)
    q, scales = quantize_int8(chunks, interpret=True)
    out = dequantize_int8(q, scales, interpret=True)
    err = np.abs(np.asarray(out) - np.asarray(chunks))
    bound = np.asarray(scales)[:, None] / 2 + 1e-7
    assert (err <= bound).all()


@pytest.mark.parametrize("nchunks,chunk,k", [(4, 128, 8), (16, 256, 32), (9, 128, 1)])
def test_chunked_topk_kernel_matches_lax(nchunks, chunk, k):
    rng = np.random.default_rng(2)
    chunks = jnp.asarray(rng.normal(size=(nchunks, chunk)), jnp.float32)
    vals, idx = chunked_topk(chunks, k, interpret=True)
    _, ref_idx = jax.lax.top_k(jnp.abs(chunks), k)
    ref_vals = jnp.take_along_axis(chunks, ref_idx, axis=1)
    np.testing.assert_array_equal(np.asarray(idx), np.asarray(ref_idx))
    np.testing.assert_allclose(np.asarray(vals), np.asarray(ref_vals))


def test_chunked_topk_tie_breaking():
    """Equal magnitudes resolve to the lower index, like lax.top_k."""
    row = jnp.zeros((1, 128), jnp.float32).at[0, 5].set(-3.0).at[0, 9].set(3.0)
    vals, idx = chunked_topk(row, 2, interpret=True)
    assert idx.tolist() == [[5, 9]]
    assert vals.tolist() == [[-3.0, 3.0]]


@pytest.mark.parametrize("shape", [(1000,), (37, 53), (8, 128)])
def test_pallas_int8_codec_parity(shape):
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=shape) * 5, jnp.float32)
    interp = PallasInt8Compressor(chunk=256, impl="interpret")
    ref = PallasInt8Compressor(chunk=256, impl="jnp")
    pi, pr = interp.compress(x), ref.compress(x)
    np.testing.assert_array_equal(np.asarray(pi.data), np.asarray(pr.data))
    np.testing.assert_allclose(np.asarray(pi.scales), np.asarray(pr.scales), rtol=1e-7)
    np.testing.assert_allclose(
        np.asarray(interp.decompress(pi)), np.asarray(ref.decompress(pr)), rtol=1e-6
    )


@pytest.mark.parametrize("shape", [(1000,), (37, 53), (4, 512)])
def test_chunked_topk_codec_parity(shape):
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.normal(size=shape), jnp.float32)
    interp = ChunkedTopKCompressor(chunk=128, k_per_chunk=8, impl="interpret")
    ref = ChunkedTopKCompressor(chunk=128, k_per_chunk=8, impl="jnp")
    pi, pr = interp.compress(x), ref.compress(x)
    np.testing.assert_array_equal(np.asarray(pi.indices), np.asarray(pr.indices))
    np.testing.assert_allclose(np.asarray(pi.values), np.asarray(pr.values))
    out = interp.decompress(pi)
    assert out.shape == shape
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref.decompress(pr)))


def test_chunked_topk_padding_tail_is_safe():
    """Padded tail beyond n must contribute nothing after decompress."""
    x = jnp.ones((100,), jnp.float32)  # pads to 128 with zeros
    codec = ChunkedTopKCompressor(chunk=128, k_per_chunk=128, impl="interpret")
    out = codec.decompress(codec.compress(x))
    np.testing.assert_allclose(np.asarray(out), np.ones(100))


def test_codec_in_choco_engine():
    """Pallas codecs drop into the consensus engine (simulated backend)."""
    from consensusml_tpu.comm import simulated
    from consensusml_tpu.consensus import ConsensusEngine, GossipConfig
    from consensusml_tpu.topology import RingTopology

    topo = RingTopology(4)
    engine = ConsensusEngine(
        GossipConfig(
            topology=topo,
            compressor=ChunkedTopKCompressor(chunk=128, k_per_chunk=32, impl="jnp"),
            gamma=0.5,
        )
    )
    rng = np.random.default_rng(5)
    x = {"w": jnp.asarray(rng.normal(size=(4, 16, 16)), jnp.float32)}
    err0 = float(engine.consensus_error_simulated(x))
    # stacked params: bucketed/fused CHOCO buffers need the worker count
    state = engine.init_state(x, world_size=4)
    w = simulated.mixing_matrix(topo)
    for _ in range(40):
        x, state = engine.round_simulated(x, state, w)
    assert float(engine.consensus_error_simulated(x)) < 0.2 * err0


def test_invalid_chunk_rejected():
    with pytest.raises(ValueError, match="multiple of 128"):
        PallasInt8Compressor(chunk=100)
    with pytest.raises(ValueError, match="k_per_chunk"):
        ChunkedTopKCompressor(chunk=128, k_per_chunk=0)


def test_chunked_topk_large_k_falls_back_to_sort():
    """k past the kernel's O(k)-pass sweet spot routes to lax.top_k while
    keeping identical chunked payload semantics."""
    from consensusml_tpu.compress import ChunkedTopKCompressor

    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.normal(size=(4, 512)), jnp.float32)
    big = ChunkedTopKCompressor(chunk=256, k_per_chunk=128, impl="pallas")
    ref = ChunkedTopKCompressor(chunk=256, k_per_chunk=128, impl="jnp")
    p_big, p_ref = big.compress(x), ref.compress(x)
    np.testing.assert_array_equal(np.asarray(p_big.indices), np.asarray(p_ref.indices))
    np.testing.assert_allclose(np.asarray(p_big.values), np.asarray(p_ref.values))


@pytest.mark.parametrize("nchunks,chunk,k", [(4, 128, 8), (7, 256, 3), (1, 128, 1)])
def test_chunk_scatter_kernel_matches_dense(nchunks, chunk, k):
    """chunk_scatter (the structured scatter that replaces XLA's generic
    .at[].add on the CHOCO receive path) against the obvious dense math."""
    from consensusml_tpu.compress.kernels import chunk_scatter

    rng = np.random.default_rng(10)
    vals = jnp.asarray(rng.normal(size=(nchunks, k)), jnp.float32)
    # distinct in-chunk positions per row, like top-k emits
    idx = jnp.asarray(
        np.stack([
            rng.choice(chunk, size=k, replace=False) for _ in range(nchunks)
        ]),
        jnp.int32,
    )
    want = np.zeros((nchunks, chunk), np.float32)
    for r in range(nchunks):
        for j in range(k):
            want[r, int(idx[r, j])] += 0.3 * float(vals[r, j])
    acc = jnp.asarray(rng.normal(size=(nchunks, chunk)), jnp.float32)
    got = chunk_scatter(vals, idx, chunk, acc, weight=0.3, interpret=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(acc) + want, rtol=1e-6, atol=1e-6
    )
    got0 = chunk_scatter(vals, idx, chunk, weight=0.3, interpret=True)
    np.testing.assert_allclose(np.asarray(got0), want, rtol=1e-6, atol=1e-6)


def test_kernel_scatter_payload_parity_with_fallback():
    """ChunkedTopKCompressor's kernel scatter path == the generic
    .at[].add fallback, including a non-chunk-aligned (padded tail) n."""
    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.normal(size=(3, 70)), jnp.float32)  # 210 % 128 != 0
    interp = ChunkedTopKCompressor(chunk=128, k_per_chunk=8, impl="interpret")
    ref = ChunkedTopKCompressor(chunk=128, k_per_chunk=8, impl="jnp")
    p = interp.compress(x)
    assert interp._kernel_scatter(p, None, 1.0) is not None  # kernel engaged
    np.testing.assert_allclose(
        np.asarray(interp.decompress(p)),
        np.asarray(ref.decompress(ref.compress(x))),
        rtol=1e-6, atol=1e-6,
    )
    acc = jnp.asarray(rng.normal(size=(3, 70)), jnp.float32)
    got = interp.decompress_accumulate(p, acc, 0.25)
    want = ref.decompress_accumulate(ref.compress(x), acc, 0.25)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-6)


def test_block_rows_vmem_budget():
    """Wide chunks must shrink the row block so no VMEM buffer exceeds
    the budget (ADVICE r3: a hard 256-row block at chunk=65536 is a
    64 MiB buffer that can never fit)."""
    from consensusml_tpu.compress.kernels import (
        _BLOCK_ELEM_BUDGET,
        _SUBLANE_F32,
        _SUBLANE_I8,
        _block_rows,
    )

    # shipped sizes keep the measured 256-row blocking
    assert _block_rows(100000, 512, _SUBLANE_F32) == 256
    assert _block_rows(100000, 2048, _SUBLANE_F32) == 256
    # wide chunks honor the budget
    for chunk in (4096, 16384, 65536):
        br = _block_rows(100000, chunk, _SUBLANE_F32)
        assert br * chunk <= _BLOCK_ELEM_BUDGET
        assert br % _SUBLANE_F32 == 0 and br >= _SUBLANE_F32
    # the sublane multiple is a hard floor even past the budget
    assert _block_rows(100000, 65536, _SUBLANE_I8) == _SUBLANE_I8
    # small inputs never exceed their row count
    assert _block_rows(8, 512, _SUBLANE_F32) == 8


def test_wide_chunk_kernels_roundtrip():
    """Kernels stay correct when the budget shrinks the block (multi-
    block grid over a 16384-wide chunk)."""
    rng = np.random.default_rng(7)
    chunks = jnp.asarray(rng.normal(size=(100, 16384)), jnp.float32)

    q, s = quantize_int8(chunks, interpret=True)
    ref = Int8Compressor(chunk=16384).compress(chunks.reshape(-1))
    np.testing.assert_array_equal(np.asarray(q).reshape(-1), np.asarray(ref.data))
    # 1-ulp scale slack: the blocked max reduces the 16384-wide row in a
    # different association order than the jnp reference
    np.testing.assert_allclose(np.asarray(s), np.asarray(ref.scales), rtol=1e-6)
    out = dequantize_int8(q, s, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out),
        np.asarray(q, np.float32) * np.asarray(s)[:, None],
        rtol=1e-6,
    )

    k = 4
    vals, idx = chunked_topk(chunks, k, interpret=True)
    _, ref_idx = jax.lax.top_k(jnp.abs(chunks), k)
    ref_vals = jnp.take_along_axis(chunks, ref_idx, axis=1)
    np.testing.assert_array_equal(np.asarray(idx), np.asarray(ref_idx))
    np.testing.assert_allclose(np.asarray(vals), np.asarray(ref_vals))

    from consensusml_tpu.compress.kernels import chunk_scatter

    dense = chunk_scatter(vals, idx, 16384, interpret=True)
    ref_dense = np.zeros((100, 16384), np.float32)
    np.put_along_axis(ref_dense, np.asarray(idx), np.asarray(vals), axis=1)
    np.testing.assert_allclose(np.asarray(dense), ref_dense, rtol=1e-6)
