"""Refcounted prefix-block sharing + copy-on-write (ISSUE 18).

The pinned properties:

- **Refcount soundness** — under randomized alloc/adopt/pin/extend/
  shrink/release churn the free list and the slot-owned multiset
  partition the physical blocks exactly (``BlockPool.check()`` after
  every op), and everything drains back to a full free list.
- **Index semantics** — the content-addressed index keys on
  ``(tenant, generation, running-hash)``: chained digests make a match
  position-dependent, tenants never see each other's blocks, stale
  generations drop at hot-swap, and block reuse eagerly invalidates.
- **Bit-exact parity** — the SAME prompts through a prefix-cache-on
  engine and a prefix-cache-off engine produce identical token streams
  (both model families, greedy and sampled, speculative and plain),
  with zero recompiles after warmup: one prefix-prefill executable per
  SUFFIX bucket.
- **Divergence + pressure** — a full-match admission copies-on-write
  instead of mutating the shared block; recompute-preempted streams
  re-admit THROUGH the cache and still finish token-identical to a
  never-evicting engine.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from consensusml_tpu.serve import Engine, ServeConfig, SpecConfig
from consensusml_tpu.serve import pool as P

pytestmark = pytest.mark.serving


def _tiny_gpt2():
    from consensusml_tpu.models.gpt2 import GPT2Config, GPT2LM

    return GPT2LM(
        config=GPT2Config(
            vocab_size=64, hidden=32, layers=2, heads=2, max_len=32,
            dropout=0.0,
        )
    )


def _tiny_llama():
    from consensusml_tpu.models.llama import llama_tiny

    return llama_tiny(max_len=32)


def _init(model, seed=0):
    return model.init(jax.random.key(seed), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]


# ---------------------------------------------------------------------------
# BlockPool refcounts under churn
# ---------------------------------------------------------------------------


def test_refcounted_pool_randomized_churn_with_sharing():
    """Randomized alloc/adopt/pin/extend/shrink/release churn with
    check() after EVERY op: the free list and the Σ slot-owned multiset
    (plus pins) partition the physical blocks at all times, shared
    blocks survive their first releaser, and the pool drains clean."""
    rng = np.random.default_rng(0)
    pool = P.BlockPool(num_slots=6, max_len=32, block_size=4, num_blocks=24)
    live: set[int] = set()
    pinned: list[int] = []
    adopts = 0
    for step in range(400):
        op = rng.integers(0, 6)
        if op == 0 and len(live) < pool.num_slots:  # fresh admission
            slot = next(s for s in range(pool.num_slots) if s not in live)
            pool.begin(slot)
            donor = int(rng.choice(sorted(live))) if live else None
            if donor is not None and rng.random() < 0.6:
                owned = pool.owned(donor)
                k = int(
                    rng.integers(1, min(len(owned), pool.blocks_per_slot - 2) + 1)
                )
                before = [pool.refcount(b) for b in owned[:k]]
                pool.adopt(slot, owned[:k])
                adopts += 1
                for b, r in zip(owned[:k], before):
                    assert pool.refcount(b) == r + 1
            try:
                pool.extend(slot, int(rng.integers(1, 3)))
            except P.NoFreeBlocks:
                pool.release(slot)
            else:
                live.add(slot)
        elif op == 1 and live:  # grow
            slot = int(rng.choice(sorted(live)))
            if len(pool.owned(slot)) < pool.blocks_per_slot:
                try:
                    pool.extend(slot)
                except P.NoFreeBlocks:
                    pass
        elif op == 2 and live:  # shrink toward the head
            slot = int(rng.choice(sorted(live)))
            n = len(pool.owned(slot))
            pool.shrink(slot, int(rng.integers(1, n + 1)))
        elif op == 3 and live:  # pin a shared-candidate block
            slot = int(rng.choice(sorted(live)))
            b = int(rng.choice(pool.owned(slot)))
            pool.pin(b)
            pinned.append(b)
        elif op == 4 and pinned:
            pool.unpin(pinned.pop(int(rng.integers(0, len(pinned)))))
        elif op == 5 and live:  # terminal release
            slot = int(rng.choice(sorted(live)))
            pool.release(slot)
            live.discard(slot)
        pool.check()
    assert adopts > 0, "churn never exercised sharing"
    for b in pinned:
        pool.unpin(b)
    for slot in sorted(live):
        pool.release(slot)
    pool.check()
    assert pool.free_blocks == pool.usable_blocks
    assert pool.shared_blocks == 0


def test_pool_adopt_and_pin_reject_misuse():
    pool = P.BlockPool(num_slots=2, max_len=16, block_size=4, num_blocks=9)
    blocks = pool.alloc(0, 2)
    with pytest.raises(RuntimeError):  # adopt without begin()
        pool.adopt(1, blocks)
    pool.begin(1)
    with pytest.raises(ValueError):  # the trash block is never adoptable
        pool.adopt(1, [P.TRASH_BLOCK])
    pool.adopt(1, blocks[:1])
    assert pool.refcount(blocks[0]) == 2
    assert pool.shared_blocks == 1
    with pytest.raises(RuntimeError):  # double-adopt of a held block
        pool.adopt(1, blocks[:1])
    # adopting a FREE in-range block is the legal revive path (a cached
    # prefix block coming back off the free list)
    parked = pool._free[-1]
    pool.adopt(1, [parked])
    assert pool.refcount(parked) == 1 and parked not in pool._free
    with pytest.raises(RuntimeError):  # unpin without pin
        pool.unpin(blocks[1])
    # releasing the original owner keeps the shared block alive
    pool.release(0)
    pool.check()
    assert pool.refcount(blocks[0]) == 1
    pool.release(1)
    pool.check()
    assert pool.free_blocks == pool.usable_blocks


# ---------------------------------------------------------------------------
# PrefixIndex content addressing
# ---------------------------------------------------------------------------


def test_prefix_index_chain_tenant_generation_semantics():
    idx = P.PrefixIndex(block_size=4)
    ids = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3]  # 2 full chunks + partial tail
    assert idx.lookup("a", 0, ids) == []
    assert idx.insert("a", 0, ids, [7, 8, 11]) == 2  # tail never indexed
    assert len(idx) == 2 and idx.indexed_blocks == 2
    assert idx.lookup("a", 0, ids) == [7, 8]
    # partial tails don't match; shorter aligned prefixes do
    assert idx.lookup("a", 0, ids[:6]) == [7]
    # running hash: same SECOND chunk behind a different first chunk
    # must not match at position 2
    other = [9, 9, 9, 9] + ids[4:8]
    assert idx.lookup("a", 0, other) == []
    # divergence inside chunk 2 stops the chain after chunk 1
    div = ids[:4] + [9, 9, 9, 9]
    assert idx.lookup("a", 0, div) == [7]
    # tenant + generation namespacing
    assert idx.lookup("b", 0, ids) == []
    assert idx.lookup("a", 1, ids) == []
    # first writer wins on re-insert
    assert idx.insert("a", 0, ids, [20, 21]) == 0
    assert idx.lookup("a", 0, ids) == [7, 8]
    # block reuse eagerly invalidates just the entries naming it
    assert idx.invalidate_block(8) == 1
    assert idx.invalidations == 1
    assert idx.lookup("a", 0, ids) == [7]
    assert idx.cached(7) and not idx.cached(8)
    # hot-swap: stale generations drop wholesale
    idx.insert("a", 1, ids, [30, 31])
    assert idx.drop_stale(1) == 1  # the surviving gen-0 entry
    assert idx.lookup("a", 0, ids) == []
    assert idx.lookup("a", 1, ids) == [30, 31]


# ---------------------------------------------------------------------------
# Engine parity: prefix cache on vs off, bit for bit
# ---------------------------------------------------------------------------


def _serve_all(model, params, cfg, jobs, spec=None):
    """Submit ``jobs`` (ids, max_new, kwargs) sequentially so later
    shared-prefix jobs deterministically find the earlier insertions.
    Returns (token streams, per-request hit blocks, stats, warm)."""
    with Engine(model, params, cfg, spec_decode=spec) as eng:
        warm = eng.warmup()
        results = [
            eng.submit(ids, max_new, **kw).result(timeout=120)
            for ids, max_new, kw in jobs
        ]
        stats = eng.stats()
        eng._pool.check()
        assert stats["pool"]["free_blocks"] == stats["pool"]["usable_blocks"]
    toks = [r.tokens for r in results]
    hits = [r.prefix_hit_blocks for r in results]
    return toks, hits, stats, warm


# fast/slow tiering (tests/conftest.py, round-8): a prefix-on engine
# pays ~2x warmup (one extra prefill executable per suffix bucket, plus
# draft twins under spec), so every on-vs-off pair here costs 13-24s and
# the fast tier has no room for five of them. The gpt2 bit-exact parity
# run — the acceptance criterion itself: shared-prefix streams identical
# on vs off, hit accounting pinned, zero recompiles — STAYS fast along
# with the sub-second pool/index unit tests; the llama family twin, spec
# composition, COW divergence, eviction re-admission, hot-swap
# invalidation and tenant isolation ride the slow tier per the round-7
# "≥10s with a sibling covering the surface" rule (the fast parity run
# drives the same _prefix_plan/adopt/insert machinery end to end).
@pytest.mark.parametrize(
    "family",
    ["gpt2", pytest.param("llama", marks=pytest.mark.slow)],
)
def test_engine_prefix_parity_bit_exact(family):
    """Shared-prefix traffic (greedy AND sampled) through a prefix-on
    engine matches the prefix-off engine token for token, while the hit
    accounting shows the shared blocks were adopted, not recomputed."""
    model = _tiny_gpt2() if family == "gpt2" else _tiny_llama()
    vocab = model.config.vocab_size
    params = _init(model)
    rng = np.random.default_rng(18)
    shared = rng.integers(0, vocab - 1, size=16).tolist()  # 2 full blocks
    jobs = []
    for i, n in enumerate((1, 3, 5, 8)):  # distinct unshared suffixes
        suffix = rng.integers(0, vocab - 1, size=n).tolist()
        kw = {} if i % 2 == 0 else {"temperature": 0.9, "seed": 100 + i}
        jobs.append((shared + suffix, 6, kw))
    jobs.append((rng.integers(0, vocab - 1, size=5).tolist(), 6, {}))

    cfg = dict(num_slots=4, max_len=32, kv_impl="paged", block_size=8)
    on, on_hits, on_stats, warm = _serve_all(
        model, params, ServeConfig(prefix_cache=True, **cfg), jobs
    )
    off, off_hits, off_stats, _ = _serve_all(
        model, params, ServeConfig(prefix_cache=False, **cfg), jobs
    )
    assert on == off
    pc = on_stats["prefix_cache"]
    # job 0 inserts the 2 shared chunks; jobs 1-3 adopt both
    assert pc["hits"] == 3 and pc["hit_blocks"] == 6
    assert pc["misses"] == 2  # job 0 and the unrelated prompt
    assert on_hits == [0, 2, 2, 2, 0]
    assert sum(on_hits) == pc["hit_blocks"]
    assert off_stats.get("prefix_cache") is None and off_hits == [0] * 5
    # prefix hits prefill only the SUFFIX bucket: fewer tokens computed
    assert (
        on_stats["prefill_tokens_computed"]
        < off_stats["prefill_tokens_computed"]
    )
    # one executable per suffix bucket, all paid during warmup
    assert on_stats["compile_counts"] == warm


@pytest.mark.slow
def test_engine_cow_on_full_match_divergence():
    """A FULL-match admission (every prompt block indexed) diverges
    inside its last block: the engine copies that block on write and
    recomputes only the final token — streams stay bit-identical to the
    prefix-off engine and the donor's blocks are never mutated."""
    model = _tiny_gpt2()
    params = _init(model)
    rng = np.random.default_rng(3)
    prompt = rng.integers(0, 63, size=16).tolist()  # exactly 2 blocks
    jobs = [(prompt, 6, {}), (prompt, 6, {}), (prompt, 6, {})]
    cfg = dict(num_slots=4, max_len=32, kv_impl="paged", block_size=8)
    on, _, on_stats, warm = _serve_all(
        model, params, ServeConfig(prefix_cache=True, **cfg), jobs
    )
    off, _, _, _ = _serve_all(
        model, params, ServeConfig(prefix_cache=False, **cfg), jobs
    )
    assert on == off
    assert on[0] == on[1] == on[2]  # greedy: identical streams
    pc = on_stats["prefix_cache"]
    assert pc["hits"] == 2 and pc["cow_copies"] == 2
    assert on_stats["compile_counts"] == warm


@pytest.mark.slow
def test_spec_decode_prefix_parity_bit_exact():
    """Speculative decode (self-draft: acceptance 1.0) composes with the
    prefix cache — draft pages share the pool's block table, so a hit
    also skips the draft's shared prefill — and streams stay bit-exact
    vs the prefix-off speculative engine."""
    model = _tiny_gpt2()
    params = _init(model)
    rng = np.random.default_rng(11)
    shared = rng.integers(0, 63, size=16).tolist()
    jobs = [
        (shared + rng.integers(0, 63, size=n).tolist(), 6,
         {"temperature": 1.2, "seed": 40 + n})
        for n in (2, 4, 7)
    ]
    cfg = dict(num_slots=2, max_len=32, kv_impl="paged", block_size=8)
    spec = SpecConfig(model=model, params=params, k=2)
    on, _, on_stats, warm = _serve_all(
        model, params, ServeConfig(prefix_cache=True, **cfg), jobs, spec=spec
    )
    off, _, _, _ = _serve_all(
        model, params, ServeConfig(prefix_cache=False, **cfg), jobs,
        spec=SpecConfig(model=model, params=params, k=2),
    )
    assert on == off
    pc = on_stats["prefix_cache"]
    assert pc["hits"] == 2 and pc["hit_blocks"] == 4
    assert on_stats["spec"]["acceptance_rate"] == 1.0
    assert on_stats["compile_counts"] == warm


@pytest.mark.slow
def test_preemption_readmission_through_prefix_cache():
    """Recompute-preempted streams re-admit THROUGH the cache: the
    shared prompt block is adopted at re-admission instead of being
    re-prefilled, and the tight engine still finishes token-identical
    to a never-evicting one."""
    model = _tiny_gpt2()
    params = _init(model)
    rng = np.random.default_rng(9)
    shared = rng.integers(0, 63, size=8).tolist()  # 1 full block
    prompts = [
        shared + rng.integers(0, 63, size=n).tolist() for n in (2, 5, 8, 11)
    ]
    # peak PHYSICAL demand counts the shared block once: 1 shared +
    # (2+2+3+3) unshared = 11 blocks vs the tight pool's 9 usable, and
    # the lockstep decode batch reaches peak simultaneously — eviction
    # pressure survives the very sharing this test exercises (max_new=6
    # would not: sharing alone shrinks demand to fit, which is the perf
    # story but not the re-admission one)
    max_new = 10

    def serve(num_blocks):
        cfg = ServeConfig(
            num_slots=4, max_len=32, kv_impl="paged", block_size=8,
            num_blocks=num_blocks, prefix_cache=True,
        )
        with Engine(model, params, cfg) as eng:
            eng.warmup()
            handles = [eng.submit(p, max_new) for p in prompts]
            results = [h.result(timeout=120) for h in handles]
            stats = eng.stats()
            eng._pool.check()
            assert (
                stats["pool"]["free_blocks"] == stats["pool"]["usable_blocks"]
            )
        return [r.tokens for r in results], stats

    tight, tight_stats = serve(num_blocks=10)
    roomy, roomy_stats = serve(num_blocks=0)
    assert roomy_stats["evictions"] == 0
    assert tight_stats["evictions"] > 0
    assert tight == roomy
    assert all(len(t) == max_new for t in tight)
    # admissions after the first find the shared block (initial waves
    # AND re-admitted continuations both resolve through the index)
    assert tight_stats["prefix_cache"]["hits"] >= 1
    assert roomy_stats["prefix_cache"]["hits"] >= 3


# ---------------------------------------------------------------------------
# Invalidation boundaries: hot swap + tenants
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_hot_swap_drops_stale_prefix_generation():
    """A generation flip invalidates the whole index: entries minted
    under the old weights are unreachable (lookups key on the live
    generation) and drop_stale reclaims them at flip time, so the first
    post-swap admission re-prefills from scratch."""
    model = _tiny_gpt2()
    params = _init(model)
    prompt = np.random.default_rng(2).integers(0, 63, size=16).tolist()
    with Engine(
        model, params,
        ServeConfig(num_slots=2, max_len=32, kv_impl="paged",
                    prefix_cache=True),
    ) as eng:
        eng.warmup()
        eng.submit(prompt, 2).result(timeout=120)  # miss: inserts gen 0
        eng.submit(prompt, 2).result(timeout=120)  # hit
        pc = eng.stats()["prefix_cache"]
        assert pc["hits"] == 1 and pc["entries"] == 2

        from consensusml_tpu.serve.pool.hotswap import StagedSwap

        class OneShotWatcher:
            def __init__(self):
                self.staged = StagedSwap(1, params, {})

            def take(self):
                sw, self.staged = self.staged, None
                return sw

            def reject(self, staged=None):
                raise AssertionError("identical tree must flip")

            def stop(self):
                pass

        eng._watcher = OneShotWatcher()
        # the flip happens between decode steps; drive one throwaway
        # request through so the loop observes the staged generation
        eng.submit([1, 2, 3], 2).result(timeout=120)
        deadline = 120
        while eng.generation != 1 and deadline > 0:
            import time as _t

            _t.sleep(0.05)
            deadline -= 1
        assert eng.generation == 1
        assert len(eng._prefix) == 0  # gen-0 entries dropped at flip
        eng.submit(prompt, 2).result(timeout=120)  # stale gen: miss
        eng.submit(prompt, 2).result(timeout=120)  # re-indexed: hit
        pc = eng.stats()["prefix_cache"]
    assert pc["hits"] == 2 and pc["misses"] == 3


@pytest.mark.slow
def test_cross_tenant_prefix_isolation():
    """Identical prompts under different tenants never share cache
    entries: tenant B's first admission is a MISS even though tenant A
    already indexed the same bytes — while the served streams (a pure
    function of the weights) stay identical across tenants."""
    model = _tiny_gpt2()
    params = _init(model)
    prompt = np.random.default_rng(4).integers(0, 63, size=16).tolist()
    with Engine(
        model, params,
        ServeConfig(num_slots=2, max_len=32, kv_impl="paged",
                    prefix_cache=True),
    ) as eng:
        eng.warmup()
        a1 = eng.submit(prompt, 4, tenant="acme").result(timeout=120)
        a2 = eng.submit(prompt, 4, tenant="acme").result(timeout=120)
        b1 = eng.submit(prompt, 4, tenant="bolt").result(timeout=120)
        b2 = eng.submit(prompt, 4, tenant="bolt").result(timeout=120)
        pc = eng.stats()["prefix_cache"]
        eng._pool.check()
    assert a1.tokens == a2.tokens == b1.tokens == b2.tokens
    assert b1.prefix_hit_blocks == 0  # isolation: no cross-tenant hit
    assert a2.prefix_hit_blocks == 2 and b2.prefix_hit_blocks == 2
    assert pc["hits"] == 2 and pc["misses"] == 2
    assert pc["entries"] == 4  # 2 chunks per tenant namespace
