"""Ring flash attention: the Pallas-kernel ring path vs dense attention
on the gathered sequence (interpreter mode on the 8-device CPU mesh)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from consensusml_tpu.models import flash_attention as fa_mod
from consensusml_tpu.models.attention import dot_product_attention
from consensusml_tpu.parallel import ring_flash_attention


@pytest.fixture(autouse=True)
def small_blocks(monkeypatch):
    monkeypatch.setattr(fa_mod, "_BQ", 16)
    monkeypatch.setattr(fa_mod, "_BK", 16)


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("sp",))


def _run_ring(q, k, v, n, causal):
    mesh = _mesh(n)

    @jax.jit
    @functools.partial(
        jax.shard_map, mesh=mesh, in_specs=P(None, "sp"),
        out_specs=P(None, "sp"),
        # the Pallas HLO interpreter mixes varying/unvarying operands in
        # its internal slicing; real TPU compiles don't take this path
        check_vma=False,
    )
    def f(q, k, v):
        return ring_flash_attention(q, k, v, "sp", causal=causal, interpret=True)

    shard = NamedSharding(mesh, P(None, "sp"))
    return f(*(jax.device_put(x, shard) for x in (q, k, v)))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_matches_dense(causal):
    n, b, s, h, d = 4, 1, 64, 2, 64  # 16 tokens per device
    rng = np.random.default_rng(0)
    q, k, v = (
        jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32) for _ in range(3)
    )
    want = dot_product_attention(q, k, v, causal=causal, dtype=jnp.float32, impl="dense")
    got = _run_ring(q, k, v, n, causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=3e-5, atol=3e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_grads_match_dense(causal):
    n, b, s, h, d = 4, 1, 64, 1, 64
    rng = np.random.default_rng(1)
    q, k, v = (
        jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32) for _ in range(3)
    )
    mesh = _mesh(n)
    shard = NamedSharding(mesh, P(None, "sp"))

    @jax.jit
    @functools.partial(
        jax.shard_map, mesh=mesh, in_specs=P(None, "sp"), out_specs=P(),
        check_vma=False,
    )
    def ring_loss_grad(q, k, v):
        # LOCAL loss per device: the global loss is the sum of local
        # losses, and the ring backward already aggregates each kv
        # block's gradient across all devices' cotangents — a psum
        # inside the differentiated region would double-seed under
        # check_vma=False
        def loss(q, k, v):
            o = ring_flash_attention(q, k, v, "sp", causal=causal, interpret=True)
            return jnp.sum(o**2)

        g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        # grads are sequence-sharded; gather for comparison
        return jax.tree.map(
            lambda x: jax.lax.all_gather(x, "sp", axis=1, tiled=True), g
        )

    g_ring = ring_loss_grad(
        *(jax.device_put(x, shard) for x in (q, k, v))
    )

    def dense_loss(q, k, v):
        o = dot_product_attention(q, k, v, causal=causal, dtype=jnp.float32, impl="dense")
        return jnp.sum(o**2)

    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for name, a, b_ in zip("qkv", g_ring, g_dense):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), rtol=5e-4, atol=5e-4,
            err_msg=f"d{name}",
        )


def test_ring_flash_padded_blocks():
    # per-device block (12) not a multiple of the kernel blocks (16)
    n, b, s, h, d = 4, 1, 48, 1, 64
    rng = np.random.default_rng(2)
    q, k, v = (
        jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32) for _ in range(3)
    )
    want = dot_product_attention(q, k, v, causal=True, dtype=jnp.float32, impl="dense")
    got = _run_ring(q, k, v, n, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=3e-5, atol=3e-5)


def test_ring_flash_rejects_mismatched_blocks():
    q = jnp.zeros((1, 16, 1, 64))
    k = jnp.zeros((1, 32, 1, 64))
    with pytest.raises(ValueError, match="equal block shapes"):
        ring_flash_attention(q, k, k, "sp")


def test_ring_flash_padded_blocks_grads():
    """Backward through padded per-device blocks (s_blk=12 < block=16):
    the zero-do padded rows must contribute nothing to dq/dk/dv."""
    n, b, s, h, d = 4, 1, 48, 1, 64
    rng = np.random.default_rng(6)
    q, k, v = (
        jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32) for _ in range(3)
    )
    mesh = _mesh(n)
    shard = NamedSharding(mesh, P(None, "sp"))

    @jax.jit
    @functools.partial(
        jax.shard_map, mesh=mesh, in_specs=P(None, "sp"), out_specs=P(),
        check_vma=False,
    )
    def ring_grads(q, k, v):
        def loss(q, k, v):
            o = ring_flash_attention(q, k, v, "sp", causal=True, interpret=True)
            return jnp.sum(o**2)

        g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        return jax.tree.map(
            lambda x: jax.lax.all_gather(x, "sp", axis=1, tiled=True), g
        )

    g_ring = ring_grads(*(jax.device_put(x, shard) for x in (q, k, v)))

    def dense_loss(q, k, v):
        o = dot_product_attention(q, k, v, causal=True, dtype=jnp.float32, impl="dense")
        return jnp.sum(o**2)

    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for name, a, b_ in zip("qkv", g_ring, g_dense):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), rtol=5e-4, atol=5e-4,
            err_msg=f"d{name}",
        )
