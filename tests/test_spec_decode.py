"""In-jit sampling + speculative decode on the paged engine (ISSUE 13).

The pinned properties:

- **Sampling semantics** — temperature 0 is bit-compatible greedy argmax
  of the SAME executable; top-p masks to the nucleus; streams are a pure
  function of ``(seed, position)`` so replay is deterministic.
- **Distribution equality** — speculative decode with a draft that IS
  the target reproduces target-only sampling BIT FOR BIT under the
  shared key schedule (both model families, sampled and greedy), with
  acceptance exactly 1.0 and zero recompiles after warmup.
- **Rollback invariants** — under a real (disagreeing) draft, rejected
  suffixes roll back by host bookkeeping only: ``BlockPool.check()``
  holds through randomized accept/reject churn, tight pools preempt
  mid-draft streams by recompute and every stream still completes.
- **Protocol + artifacts** — per-request ``temperature``/``top_p``/
  ``seed``/``eos_id`` ride the line-JSON wire and echo on the terminal
  record; ``export_draft`` installs the draft artifact the hot-swap
  watcher restages with the parent generation.
"""

import json
import socket

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from consensusml_tpu.serve import Engine, ServeConfig, SpecConfig
from consensusml_tpu.serve import decode as D
from consensusml_tpu.serve import pool as P

pytestmark = pytest.mark.serving


def _tiny_gpt2(**over):
    from consensusml_tpu.models.gpt2 import GPT2Config, GPT2LM

    kw = dict(
        vocab_size=64, hidden=32, layers=2, heads=2, max_len=32, dropout=0.0
    )
    kw.update(over)
    return GPT2LM(config=GPT2Config(**kw))


def _tiny_llama():
    from consensusml_tpu.models.llama import llama_tiny

    return llama_tiny(max_len=32)


def _init(model, seed=0):
    return model.init(jax.random.key(seed), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]


def _draft_pair():
    """A target and a genuinely DIFFERENT (cheaper, disagreeing) draft."""
    target = _tiny_gpt2()
    draft = _tiny_gpt2(hidden=16, layers=1)
    return target, _init(target), draft, _init(draft, seed=1)


# ---------------------------------------------------------------------------
# Sampling unit semantics
# ---------------------------------------------------------------------------


def test_adjusted_probs_greedy_topp_and_determinism():
    from consensusml_tpu.serve.sampling import (
        adjusted_probs,
        sample_token,
    )

    logits = jnp.asarray([[2.0, 1.0, 0.5, -1.0], [0.0, 3.0, 2.9, -2.0]])
    # temperature 0: exact one-hot at argmax
    greedy = adjusted_probs(
        logits, jnp.zeros((2,)), jnp.ones((2,))
    )
    np.testing.assert_array_equal(
        np.argmax(np.asarray(greedy), -1), [0, 1]
    )
    assert np.asarray(greedy).max() == 1.0
    # top-p keeps the smallest prefix reaching the mass; the rest is 0
    nucleus = np.asarray(
        adjusted_probs(logits, jnp.ones((2,)), jnp.full((2,), 0.5))
    )
    assert nucleus[0, 3] == 0.0 and nucleus[1, 3] == 0.0
    np.testing.assert_allclose(nucleus.sum(-1), 1.0, rtol=1e-6)
    # greedy sampling through the categorical is argmax, key regardless
    seeds = jnp.asarray([7, 8], jnp.uint32)
    pos = jnp.asarray([3, 9], jnp.int32)
    toks = sample_token(logits, jnp.zeros((2,)), jnp.ones((2,)), seeds, pos)
    np.testing.assert_array_equal(np.asarray(toks), [0, 1])
    # sampled draws are a pure function of (seed, position)
    t1 = sample_token(logits, jnp.ones((2,)), jnp.ones((2,)), seeds, pos)
    t2 = sample_token(logits, jnp.ones((2,)), jnp.ones((2,)), seeds, pos)
    np.testing.assert_array_equal(np.asarray(t1), np.asarray(t2))
    t3 = sample_token(
        logits, jnp.ones((2,)), jnp.ones((2,)), seeds + 1, pos
    )
    assert not np.array_equal(np.asarray(t1), np.asarray(t3))


def test_sampled_engine_streams_replay_deterministically():
    model = _tiny_gpt2()
    params = _init(model)

    def stream():
        with Engine(
            model, params, ServeConfig(num_slots=2, max_len=32)
        ) as eng:
            eng.warmup()
            r = eng.submit(
                [3, 9, 2], 8, temperature=0.9, top_p=0.8, seed=1234
            ).result(timeout=60)
            assert (r.temperature, r.top_p, r.seed) == (0.9, 0.8, 1234)
            return r.tokens

    first = stream()
    assert stream() == first
    # greedy default (no sampling args) stays the argmax path
    with Engine(model, params, ServeConfig(num_slots=2, max_len=32)) as eng:
        eng.warmup()
        g1 = eng.submit([3, 9, 2], 8).result(timeout=60)
        g2 = eng.submit([3, 9, 2], 8).result(timeout=60)
    assert g1.tokens == g2.tokens and g1.temperature == 0.0


# ---------------------------------------------------------------------------
# Distribution equality: spec(self-draft) == target-only, bit for bit
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    # ~25s/combo on this box; the fast tier keeps the canonical greedy
    # gpt2 pin, the other three combos ride the slow tier (sampled-lane
    # spec coverage stays fast via the acceptance-counter test).
    "family,temperature",
    [
        ("gpt2", 0.0),
        pytest.param("gpt2", 0.7, marks=pytest.mark.slow),
        pytest.param("llama", 0.0, marks=pytest.mark.slow),
        pytest.param("llama", 0.7, marks=pytest.mark.slow),
    ],
)
def test_spec_self_draft_matches_plain_bit_for_bit(family, temperature):
    """The acceptance fixture: with draft == target, every proposal draws
    under exactly the key the plain path would use and every acceptance
    ratio is 1, so the speculative stream equals the target-only stream
    BIT FOR BIT — sampled and greedy, both families — at acceptance 1.0
    with zero recompiles after warmup."""
    model = _tiny_gpt2() if family == "gpt2" else _tiny_llama()
    params = _init(model)
    rng = np.random.default_rng(7)
    vocab = model.config.vocab_size
    prompts = [rng.integers(0, vocab - 1, size=n).tolist() for n in (2, 5, 9, 13)]

    def serve(spec):
        with Engine(
            model, params,
            ServeConfig(num_slots=4, max_len=32, kv_impl="paged"),
            spec_decode=spec,
        ) as eng:
            warm = eng.warmup()
            handles = [
                eng.submit(
                    p, 8, temperature=temperature, top_p=0.9, seed=100 + i
                )
                for i, p in enumerate(prompts)
            ]
            results = [h.result(timeout=120) for h in handles]
            return results, warm, eng.stats()

    plain, _, _ = serve(None)
    spec, warm, stats = serve(SpecConfig(model=model, params=params, k=3))
    assert [r.tokens for r in plain] == [r.tokens for r in spec]
    assert stats["spec"]["acceptance_rate"] == 1.0
    assert stats["compile_counts"] == warm  # zero recompiles after warmup
    # per-stream accounting echoes on the terminal record
    for r in spec:
        assert r.spec_proposed > 0 and r.spec_accepted == r.spec_proposed


# ---------------------------------------------------------------------------
# Acceptance-rate counter semantics
# ---------------------------------------------------------------------------


def test_acceptance_counters_and_request_traces():
    target, tparams, draft, dparams = _draft_pair()
    with Engine(
        target, tparams,
        ServeConfig(num_slots=2, max_len=32, kv_impl="paged"),
        spec_decode=SpecConfig(model=draft, params=dparams, k=4),
    ) as eng:
        eng.warmup()
        handles = [
            eng.submit([1 + i, 5, 9], 8, temperature=1.2, seed=i)
            for i in range(4)
        ]
        results = [h.result(timeout=120) for h in handles]
        stats = eng.stats()
    spec = stats["spec"]
    assert spec["rounds"] > 0
    # proposed counts k per live lane per round; accepted never exceeds it
    assert 0 <= spec["accepted"] <= spec["proposed"]
    assert spec["proposed"] <= spec["k"] * spec["rounds"] * 2  # <= k*rounds*lanes
    assert spec["acceptance_rate"] == pytest.approx(
        spec["accepted"] / spec["proposed"]
    )
    # the per-request split sums to the engine totals and rides the trace
    assert sum(r.spec_proposed for r in results) == spec["proposed"]
    assert sum(r.spec_accepted for r in results) == spec["accepted"]
    from consensusml_tpu.obs import get_request_registry

    done = {
        t.request_id: t for t in get_request_registry().completed()
    }
    for r in results:
        tr = done.get(r.request_id)
        if tr is None:
            continue  # ring shared with other tests may have evicted it
        assert tr.spec_proposed == r.spec_proposed
        assert tr.spec_accepted == r.spec_accepted
        assert tr.to_dict()["spec_accepted"] == r.spec_accepted


# ---------------------------------------------------------------------------
# Rollback-on-reject pool invariants + mid-draft preemption
# ---------------------------------------------------------------------------


def test_block_pool_shrink_rollback_invariants():
    pool = P.BlockPool(num_slots=2, max_len=32, block_size=8)
    pool.alloc(0, 1)
    pool.extend(0, 3)  # speculative window over-allocation
    assert len(pool.owned(0)) == 4
    freed = pool.shrink(0, 2)  # rejected suffix hands the tail back
    assert len(freed) == 2 and len(pool.owned(0)) == 2
    pool.check()
    # table rows past the kept prefix reset to trash
    assert list(pool.block_row(0, 4)[2:]) == [P.TRASH_BLOCK] * 2
    assert pool.shrink(0, 2) == []  # idempotent
    with pytest.raises(ValueError, match="keep_blocks"):
        pool.shrink(0, 0)
    with pytest.raises(RuntimeError, match="owns nothing"):
        pool.shrink(1, 1)
    pool.release(0)
    pool.check()


def test_spec_randomized_churn_holds_pool_invariants():
    """Randomized accept/reject churn (a disagreeing draft at high
    temperature) across admissions, growth, rollback, and release —
    the free ∪ owned partition proof must hold throughout."""
    target, tparams, draft, dparams = _draft_pair()
    rng = np.random.default_rng(11)
    prompts = [
        rng.integers(0, 63, size=2 + int(rng.integers(0, 10))).tolist()
        for _ in range(12)
    ]
    eng = Engine(
        target, tparams,
        ServeConfig(num_slots=4, max_len=32, kv_impl="paged"),
        spec_decode=SpecConfig(model=draft, params=dparams, k=3),
    )
    try:
        eng.warmup()
        handles = [
            eng.submit(p, 9, temperature=1.5, top_p=0.9, seed=i)
            for i, p in enumerate(prompts)
        ]
        results = [h.result(timeout=180) for h in handles]
        assert all(len(r.tokens) == 9 for r in results)
        eng._pool.check()
        stats = eng.stats()
        assert 0.0 < stats["spec"]["acceptance_rate"] < 1.0  # real churn
    finally:
        eng.shutdown(drain=False)


def test_tight_pool_preempts_mid_draft_stream_by_recompute():
    """A pool too small for the speculative windows preempts the
    youngest stream BETWEEN rounds (blocks freed, prompt + generated
    re-enqueued); every stream still completes with its full token
    count, and the preempted trace records the recompute."""
    target, tparams, draft, dparams = _draft_pair()
    rng = np.random.default_rng(3)
    prompts = [rng.integers(0, 63, size=n).tolist() for n in (2, 4, 7, 9, 12, 5)]
    eng = Engine(
        target, tparams,
        ServeConfig(
            num_slots=4, max_len=32, kv_impl="paged", num_blocks=9
        ),
        spec_decode=SpecConfig(model=draft, params=dparams, k=4),
    )
    try:
        eng.warmup()
        handles = [
            eng.submit(p, 10, temperature=0.9, seed=i)
            for i, p in enumerate(prompts)
        ]
        results = [h.result(timeout=180) for h in handles]
        assert all(len(r.tokens) == 10 for r in results)
        assert eng.stats()["evictions"] > 0  # pressure actually happened
        eng._pool.check()
    finally:
        eng.shutdown(drain=False)


# ---------------------------------------------------------------------------
# Wire protocol: sampling fields + echo, per-request eos
# ---------------------------------------------------------------------------


def test_line_json_carries_sampling_fields_and_echoes():
    from consensusml_tpu.serve.server import ServeServer

    model = _tiny_gpt2()
    params = _init(model)
    engine = Engine(model, params, ServeConfig(num_slots=2, max_len=32))
    engine.warmup()
    server = ServeServer(engine)
    try:
        def ask(payload):
            with socket.create_connection(server.address, timeout=60) as c:
                f = c.makefile("rwb")
                f.write(json.dumps(payload).encode() + b"\n")
                f.flush()
                toks, done = [], None
                for line in f:
                    msg = json.loads(line)
                    if msg.get("done"):
                        done = msg
                        break
                    toks.append(msg["token"])
                return toks, done

        req = {
            "ids": [4, 8, 15], "max_new_tokens": 6,
            "temperature": 0.8, "top_p": 0.9, "seed": 777,
        }
        toks1, done1 = ask(req)
        toks2, done2 = ask(req)
        assert toks1 == toks2 == done1["tokens"]  # replay on the wire
        assert done1["temperature"] == 0.8
        assert done1["top_p"] == 0.9
        assert done1["seed"] == 777
        assert done1["spec_proposed"] == 0  # non-speculative engine
        # per-request eos override: stop exactly at the chosen token
        eos = toks1[2]
        toks3, done3 = ask(dict(req, eos_id=eos))
        assert done3["finish_reason"] == "eos"
        assert toks3 == toks1[: toks3.index(eos) + 1]
    finally:
        server.shutdown(drain=False)


def test_submit_validates_sampling_args():
    model = _tiny_gpt2()
    eng = Engine(model, _init(model), ServeConfig(num_slots=1, max_len=32))
    try:
        with pytest.raises(ValueError, match="temperature"):
            eng.submit([1], 2, temperature=-0.5)
        with pytest.raises(ValueError, match="top_p"):
            eng.submit([1], 2, top_p=0.0)
        with pytest.raises(ValueError, match="top_p"):
            eng.submit([1], 2, top_p=1.5)
    finally:
        eng.shutdown(drain=False)


def test_spec_requires_paged_and_matching_vocab():
    target, tparams, draft, dparams = _draft_pair()
    with pytest.raises(ValueError, match="paged"):
        Engine(
            target, tparams,
            ServeConfig(num_slots=1, max_len=32, kv_impl="slot"),
            spec_decode=SpecConfig(model=draft, params=dparams, k=2),
        )
    other = _tiny_gpt2(vocab_size=32)
    with pytest.raises(ValueError, match="vocab"):
        Engine(
            target, tparams,
            ServeConfig(num_slots=1, max_len=32),
            spec_decode=SpecConfig(
                model=other, params=_init(other), k=2
            ),
        )
    with pytest.raises(ValueError, match="k must be"):
        SpecConfig(model=draft, params=dparams, k=0)


# ---------------------------------------------------------------------------
# Draft artifact + hot-swap pair staging
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_export_draft_load_engine_and_pair_hot_swap(tmp_path):
    """The draft rides the parent artifact's generation protocol:
    ``export_draft`` installs ``draft/``, ``load_engine(spec_k=...)``
    builds the speculative engine from the pair, and a generation bump
    restages + flips target AND draft together mid-traffic with zero
    recompiles."""
    from consensusml_tpu import configs
    from consensusml_tpu.serve.export import (
        bump_generation,
        export_draft,
        export_serving,
        serving_meta,
    )
    from consensusml_tpu.train import init_stacked_state

    bundle = configs.build("gpt2_topk", "smoke")
    state = init_stacked_state(
        bundle.cfg, bundle.init_params, jax.random.key(0), bundle.world_size
    )
    art = str(tmp_path / "art")
    export_serving(art, state, config_name="gpt2_topk", scale="smoke")
    # self-draft artifact: same config params (acceptance 1.0 fixture)
    from consensusml_tpu.serve.export import load_serving

    _meta, params, _ms = load_serving(art)
    export_draft(art, params, config_name="gpt2_topk", scale="smoke")
    assert serving_meta(art + "/draft")["role"] == "draft"

    from consensusml_tpu.serve import load_engine

    eng = load_engine(
        art,
        ServeConfig(num_slots=2, max_len=32, max_new_tokens=6),
        spec_k=2,
    )
    try:
        warm = eng.warmup()
        r1 = eng.submit([3, 7, 11], 6).result(timeout=120)
        assert len(r1.tokens) == 6
        watcher = eng.watch(art, poll_s=0.05)
        assert watcher.stage_draft
        gen0 = eng.generation
        bump_generation(art)
        # serve across the swap; the flip lands between rounds
        import time as _time

        deadline = _time.time() + 60
        while eng.generation == gen0 and _time.time() < deadline:
            eng.submit([3, 7, 11], 6).result(timeout=120)
            _time.sleep(0.05)
        assert eng.generation == gen0 + 1
        stats = eng.stats()
        assert stats["swaps"] >= 1
        assert stats["compile_counts"] == warm  # pair flip recompiled nothing
        assert stats["spec"]["acceptance_rate"] == 1.0  # draft == target
    finally:
        eng.shutdown(drain=False)
