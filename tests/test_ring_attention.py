"""Ring attention must equal single-device attention on the gathered
sequence — bidirectional and causal, including non-uniform values."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from consensusml_tpu.models.attention import dot_product_attention
from consensusml_tpu.parallel import ring_attention


def _mesh(n):
    return Mesh(np.array(jax.devices("cpu")[:n]), ("sp",))


def _run_ring(q, k, v, n, causal):
    mesh = _mesh(n)
    shard = NamedSharding(mesh, P(None, "sp"))

    @jax.jit
    @jax.shard_map(mesh=mesh, in_specs=P(None, "sp"), out_specs=P(None, "sp"))
    def f(q, k, v):
        return ring_attention(q, k, v, "sp", causal=causal)

    return np.asarray(
        f(*(jax.device_put(x, shard) for x in (q, k, v)))
    )


@pytest.mark.parametrize("causal", [False, True], ids=["full", "causal"])
@pytest.mark.parametrize("n", [2, 4, 8])
def test_ring_matches_dense(causal, n):
    rng = np.random.default_rng(0)
    b, s, h, d = 2, 32, 4, 16
    q, k, v = (
        jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32) for _ in range(3)
    )
    want = np.asarray(
        dot_product_attention(q, k, v, causal=causal, dtype=jnp.float32)
    )
    got = _run_ring(q, k, v, n, causal)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_ring_attention_bf16():
    rng = np.random.default_rng(1)
    b, s, h, d = 1, 16, 2, 8
    q, k, v = (
        jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.bfloat16) for _ in range(3)
    )
    want = np.asarray(
        dot_product_attention(q, k, v, causal=True, dtype=jnp.bfloat16), np.float32
    )
    got = _run_ring(q, k, v, 4, True).astype(np.float32)
    np.testing.assert_allclose(got, want, rtol=0.05, atol=0.05)


def test_ring_attention_long_sequence_numerics():
    """Large logits (scaled inputs) exercise the online-softmax rescaling."""
    rng = np.random.default_rng(2)
    b, s, h, d = 1, 64, 2, 8
    q = jnp.asarray(rng.normal(size=(b, s, h, d)) * 6, jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, s, h, d)) * 6, jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    want = np.asarray(dot_product_attention(q, k, v, causal=False, dtype=jnp.float32))
    got = _run_ring(q, k, v, 8, False)
    assert np.isfinite(got).all()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_sequence_parallel_training_end_to_end():
    """A tiny causal LM TRAINS under sequence parallelism: tokens sharded
    (1/8 of the sequence per device), ring attention across shards,
    psum'd loss and gradients, replicated params — loss must fall. This
    is the long-context training recipe composed end to end, not just
    the attention exactness check."""
    import optax

    from consensusml_tpu.data import SyntheticLM

    n, b, s, d, v = 8, 4, 256, 32, 64
    mesh = _mesh(n)
    shard = NamedSharding(mesh, P(None, "sp"))

    def init_params(rng):
        k1, k2, k3, k4 = jax.random.split(rng, 4)
        scale = 0.08
        return {
            "emb": scale * jax.random.normal(k1, (v, d)),
            "qkv": scale * jax.random.normal(k2, (d, 3, 1, d)),  # 1 head
            "out": scale * jax.random.normal(k3, (d, d)),
            "head": scale * jax.random.normal(k4, (d, v)),
        }

    def forward_local(params, ids_local):
        x = params["emb"][ids_local]  # (b, s/n, d)
        qkv = jnp.einsum("bsd,dche->bsche", x, params["qkv"])
        q, k, kv = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        a = ring_attention(q, k, kv, "sp", causal=True)  # (b, s/n, 1, d)
        x = x + jnp.einsum("bshe,ed->bsd", a, params["out"])
        return jnp.einsum("bsd,dv->bsv", x, params["head"])  # logits

    tx = optax.adam(1e-2)

    @jax.jit
    @jax.shard_map(
        mesh=mesh,
        in_specs=(P(), P(), P(None, "sp"), P(None, "sp")),
        out_specs=(P(), P(), P()),
    )
    def train_step(params, opt_state, ids_local, labels_local):
        def loss_fn(p):
            logits = forward_local(p, ids_local)
            logp = jax.nn.log_softmax(logits)
            nll = -jnp.take_along_axis(logp, labels_local[..., None], -1)
            # global mean: psum the shard sums, divide by global count
            return jax.lax.psum(jnp.sum(nll), "sp") / (b * s)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        # replicated params need the cross-shard gradient sum
        grads = jax.lax.psum(grads, "sp")
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    data = SyntheticLM(vocab_size=v, seq_len=s + 1)
    params = init_params(jax.random.key(0))
    opt_state = tx.init(params)
    losses = []
    for step in range(60):
        tok = data.sample(np.random.default_rng((0, step)), (b,))
        ids = jax.device_put(jnp.asarray(tok[:, :-1]), shard)
        labels = jax.device_put(jnp.asarray(tok[:, 1:]), shard)
        params, opt_state, loss = train_step(params, opt_state, ids, labels)
        losses.append(float(loss))
    assert losses[-1] < losses[0] - 0.5, (losses[0], losses[-1])
