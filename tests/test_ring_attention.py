"""Ring attention must equal single-device attention on the gathered
sequence — bidirectional and causal, including non-uniform values."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from consensusml_tpu.models.attention import dot_product_attention
from consensusml_tpu.parallel import ring_attention


def _mesh(n):
    return Mesh(np.array(jax.devices("cpu")[:n]), ("sp",))


def _run_ring(q, k, v, n, causal):
    mesh = _mesh(n)
    shard = NamedSharding(mesh, P(None, "sp"))

    @jax.jit
    @jax.shard_map(mesh=mesh, in_specs=P(None, "sp"), out_specs=P(None, "sp"))
    def f(q, k, v):
        return ring_attention(q, k, v, "sp", causal=causal)

    return np.asarray(
        f(*(jax.device_put(x, shard) for x in (q, k, v)))
    )


@pytest.mark.parametrize("causal", [False, True], ids=["full", "causal"])
@pytest.mark.parametrize("n", [2, 4, 8])
def test_ring_matches_dense(causal, n):
    rng = np.random.default_rng(0)
    b, s, h, d = 2, 32, 4, 16
    q, k, v = (
        jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32) for _ in range(3)
    )
    want = np.asarray(
        dot_product_attention(q, k, v, causal=causal, dtype=jnp.float32)
    )
    got = _run_ring(q, k, v, n, causal)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_ring_attention_bf16():
    rng = np.random.default_rng(1)
    b, s, h, d = 1, 16, 2, 8
    q, k, v = (
        jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.bfloat16) for _ in range(3)
    )
    want = np.asarray(
        dot_product_attention(q, k, v, causal=True, dtype=jnp.bfloat16), np.float32
    )
    got = _run_ring(q, k, v, 4, True).astype(np.float32)
    np.testing.assert_allclose(got, want, rtol=0.05, atol=0.05)


def test_ring_attention_long_sequence_numerics():
    """Large logits (scaled inputs) exercise the online-softmax rescaling."""
    rng = np.random.default_rng(2)
    b, s, h, d = 1, 64, 2, 8
    q = jnp.asarray(rng.normal(size=(b, s, h, d)) * 6, jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, s, h, d)) * 6, jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    want = np.asarray(dot_product_attention(q, k, v, causal=False, dtype=jnp.float32))
    got = _run_ring(q, k, v, 8, False)
    assert np.isfinite(got).all()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
