"""True multi-PROCESS training (jax.distributed + gloo CPU collectives).

VERDICT r1 flagged the multi-host path as untested. This launches two
worker.py processes — separate JAX controllers, 4 virtual CPU devices
each — that rendezvous through jax.distributed and train the 8-worker
ring config collectively: gossip ppermutes cross the process boundary
through gloo exactly as they cross hosts through DCN on a pod.
"""

import os
import socket
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch(extra):
    port = _free_port()
    env = {
        k: v for k, v in os.environ.items() if k != "XLA_FLAGS"
    }  # worker.py sets its own device count
    env["JAX_PLATFORMS"] = ""
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(REPO, "worker.py"),
             "--coordinator", f"127.0.0.1:{port}",
             "--num-processes", "2", "--process-id", str(pid),
             "--local-devices", "4", "--",
             "--config", "cifar_resnet50", "--device", "cpu",
             "--backend", "collective", *extra],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=REPO, env=env,
        )
        for pid in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=540)
        outs.append((p.returncode, out))
    return outs


def test_two_process_collective_training():
    outs = _launch(["--rounds", "3"])
    for rc, out in outs:
        assert rc == 0, out[-1200:]
        assert "global devices=8 local=4" in out
        assert "final:" in out
    # both controllers must report the SAME replicated metrics
    final = [
        [l for l in out.splitlines() if l.startswith("final:")][-1]
        for _, out in outs
    ]
    assert final[0] == final[1], final


def test_two_process_checkpoint_and_eval(tmp_path):
    """The aux paths that once assumed fully-addressable arrays: orbax
    checkpoint of a cross-process-sharded state, and held-out eval whose
    per-worker sums are sharded over both controllers."""
    ck = str(tmp_path / "ck")
    outs = _launch(["--rounds", "2", "--checkpoint-dir", ck, "--eval-batches", "2"])
    for rc, out in outs:
        assert rc == 0, out[-1500:]
        assert "eval[mean-model]" in out
    assert os.path.exists(os.path.join(ck, "step_2", "cml_meta.json"))
