"""True multi-PROCESS training (jax.distributed + gloo CPU collectives).

VERDICT r1 flagged the multi-host path as untested. This launches two
worker.py processes — separate JAX controllers, 4 virtual CPU devices
each — that rendezvous through jax.distributed and train the 8-worker
ring config collectively: gossip ppermutes cross the process boundary
through gloo exactly as they cross hosts through DCN on a pod.

Failure paths (VERDICT r2 item 9): the happy path is not what worker.py
meets on a pod. Mismatched ``--num-processes`` and an already-bound
coordinator port are rejected FAST by the pre-rendezvous handshake
(before any jax import), and a peer killed mid-run trips the survivor's
``--round-timeout`` watchdog within a bounded time instead of wedging in
a dead collective forever.
"""

import os
import signal
import socket
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch(extra):
    port = _free_port()
    env = {
        k: v for k, v in os.environ.items() if k != "XLA_FLAGS"
    }  # worker.py sets its own device count
    env["JAX_PLATFORMS"] = ""
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(REPO, "worker.py"),
             "--coordinator", f"127.0.0.1:{port}",
             "--num-processes", "2", "--process-id", str(pid),
             "--local-devices", "4", "--",
             "--config", "cifar_resnet50", "--device", "cpu",
             "--backend", "collective", *extra],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=REPO, env=env,
        )
        for pid in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=540)
        outs.append((p.returncode, out))
    return outs


@pytest.mark.slow
def test_two_process_collective_training():
    outs = _launch(["--rounds", "3"])
    for rc, out in outs:
        assert rc == 0, out[-1200:]
        assert "global devices=8 local=4" in out
        assert "final:" in out
    # both controllers must report the SAME replicated metrics
    final = [
        [l for l in out.splitlines() if l.startswith("final:")][-1]
        for _, out in outs
    ]
    assert final[0] == final[1], final


@pytest.mark.slow
def test_two_process_checkpoint_and_eval(tmp_path):
    """The aux paths that once assumed fully-addressable arrays: orbax
    checkpoint of a cross-process-sharded state, and held-out eval whose
    per-worker sums are sharded over both controllers."""
    ck = str(tmp_path / "ck")
    outs = _launch(["--rounds", "2", "--checkpoint-dir", ck, "--eval-batches", "2"])
    for rc, out in outs:
        assert rc == 0, out[-1500:]
        assert "eval[mean-model]" in out
    assert os.path.exists(os.path.join(ck, "step_2", "cml_meta.json"))


def _worker_cmd(port, pid, num, extra_worker=(), train=()):
    return [
        sys.executable, os.path.join(REPO, "worker.py"),
        "--coordinator", f"127.0.0.1:{port}",
        "--num-processes", str(num), "--process-id", str(pid),
        "--local-devices", "4", *extra_worker, "--",
        "--config", "cifar_resnet50", "--device", "cpu",
        "--backend", "collective", *train,
    ]


def _clean_env():
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = ""
    return env


def test_mismatched_num_processes_rejected_fast():
    """Disagreeing --num-processes must fail in seconds with a reasoned
    message, not hang both processes to the grpc barrier timeout."""
    port = _free_port()
    t0 = time.monotonic()
    procs = [
        subprocess.Popen(
            _worker_cmd(port, pid, num, ["--rendezvous-timeout", "60"]),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=REPO, env=_clean_env(),
        )
        # process 0 expects a 3-process world; process 1 a 2-process one
        for pid, num in ((0, 3), (1, 2))
    ]
    outs = [p.communicate(timeout=90)[0] for p in procs]
    elapsed = time.monotonic() - t0
    # the mismatch is detected on first contact, well under the timeout
    assert elapsed < 60, f"took {elapsed:.0f}s — rejection was not fast"
    for p, out in zip(procs, outs):
        assert p.returncode != 0, out[-800:]
        assert "mismatched --num-processes" in out, out[-800:]


def test_bound_coordinator_port_rejected_fast():
    """A coordinator port someone else owns must fail process 0
    immediately with a pointer at the cause, not hang."""
    squatter = socket.socket()
    squatter.bind(("127.0.0.1", 0))
    squatter.listen(1)
    port = squatter.getsockname()[1]
    try:
        proc = subprocess.run(
            _worker_cmd(port, 0, 2, ["--rendezvous-timeout", "30"]),
            capture_output=True, text=True, timeout=60, cwd=REPO,
            env=_clean_env(),
        )
    finally:
        squatter.close()
    assert proc.returncode != 0
    combined = proc.stdout + proc.stderr
    assert "unavailable" in combined and "--coordinator" in combined, (
        combined[-800:]
    )


@pytest.mark.slow
def test_peer_death_detected_within_bound():
    """Kill one process mid-run: the survivor must exit with a clean
    diagnostic inside a bounded time (the --round-timeout watchdog; a
    dead peer otherwise wedges the next gossip collective forever)."""
    port = _free_port()
    procs = [
        subprocess.Popen(
            _worker_cmd(
                port, pid, 2,
                train=["--rounds", "500", "--round-timeout", "15",
                       "--log-every", "1"],
            ),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=REPO, env=_clean_env(),
        )
        for pid in range(2)
    ]
    survivor, victim = procs
    # drain the victim's pipe so a full stdout buffer can never stall its
    # training loop (which would deadlock the survivor's collectives)
    import threading

    threading.Thread(
        target=lambda: victim.stdout.read(), daemon=True
    ).start()
    try:
        # wait until the survivor has completed at least one round (the
        # watchdog arms on the first beat, so compile time never counts)
        deadline = time.monotonic() + 300
        saw_round = False
        lines = []
        while time.monotonic() < deadline:
            line = survivor.stdout.readline()
            if not line:
                break
            lines.append(line)
            if line.startswith("[round"):
                saw_round = True
                break
        assert saw_round, "".join(lines)[-1500:]
        victim.send_signal(signal.SIGKILL)
        t0 = time.monotonic()
        rest, _ = survivor.communicate(timeout=240)
        detected_s = time.monotonic() - t0
        out = "".join(lines) + rest
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
            p.wait(timeout=30)
    assert survivor.returncode != 0, out[-1500:]
    # the watchdog prints a reasoned diagnostic and uses its own exit code
    assert "watchdog: no train round progress" in out, out[-1500:]
    assert survivor.returncode == 3, survivor.returncode
    # bounded: 15s timeout + poll granularity + fetch slack, not 540s
    assert detected_s < 120, f"took {detected_s:.0f}s to detect peer death"
