"""On-hardware Pallas kernel parity (north star: "CUDA kernels become
Pallas kernels").

These tests run the COMPILED kernels on a real TPU chip and check
numerics against the jnp reference math — the proof the interpreter-mode
tests in test_kernels.py cannot give (e.g. Mosaic's lane-alignment rules
only apply on real compiles; an earlier chunked_topk wrote one column per
iteration and passed interpreter tests while failing TPU compilation).

The suite conftest forces the CPU platform for the virtual 8-device mesh,
so these tests run in a SUBPROCESS that re-enables the TPU; the whole
module skips when no TPU is reachable. Run directly with:
    pytest tests/test_kernels_tpu.py -m tpu
"""

import json
import os
import subprocess
import sys

import pytest

pytestmark = [pytest.mark.tpu, pytest.mark.slow]

_CHILD = r"""
import json
import numpy as np
import jax
import jax.numpy as jnp

if jax.default_backend() not in ("tpu", "axon"):
    print(json.dumps({"skip": f"no TPU (backend={jax.default_backend()})"}))
    raise SystemExit(0)

from consensusml_tpu.compress.kernels import (
    chunked_topk, dequantize_int4, dequantize_int8, quantize_int4,
    quantize_int8,
)
from consensusml_tpu.compress.reference import chunk_for_quantization

out = {"backend": jax.default_backend()}
rng = np.random.default_rng(0)

chunks = jnp.asarray(rng.normal(size=(1024, 512)), jnp.float32)
q, s = quantize_int8(chunks)
refc, refs, inv, _ = chunk_for_quantization(chunks, 512)
q_ref = np.clip(
    np.rint(np.asarray(refc) * np.asarray(inv)[:, None]), -127, 127
).astype(np.int8)
out["quant_exact"] = bool(np.array_equal(np.asarray(q), q_ref))
out["scales_exact"] = bool(np.allclose(np.asarray(s), np.asarray(refs)))
d = dequantize_int8(q, s)
out["dequant_exact"] = bool(
    np.allclose(np.asarray(d), np.asarray(q, np.float32) * np.asarray(s)[:, None])
)

from consensusml_tpu.compress.reference import Int4Compressor
chunks4 = jnp.asarray(rng.normal(size=(96, 256)), jnp.float32)
p4, s4 = quantize_int4(chunks4)
ref4 = Int4Compressor(chunk=256).compress(chunks4.reshape(-1))
out["int4_pack_exact"] = bool(
    np.array_equal(np.asarray(p4).reshape(-1), np.asarray(ref4.data))
)
d4 = dequantize_int4(p4, s4)
ref_dec = Int4Compressor(chunk=256).decompress(ref4)
out["int4_roundtrip_ok"] = bool(
    np.allclose(np.asarray(d4).reshape(-1), np.asarray(ref_dec), atol=1e-5)
)

ok_topk = True
for rows, cols, k in [(1024, 512, 16), (37, 256, 5), (8, 128, 128)]:
    c = jnp.asarray(rng.normal(size=(rows, cols)), jnp.float32)
    v, i = chunked_topk(c, k)
    _, li = jax.lax.top_k(jnp.abs(c), k)
    vref = np.take_along_axis(np.asarray(c), np.asarray(li), axis=1)
    ok_topk &= bool(np.array_equal(np.asarray(i), np.asarray(li)))
    ok_topk &= bool(np.allclose(np.asarray(v), vref))
out["topk_exact"] = ok_topk

# chunk_scatter: the structured decompress/accumulate kernel, compiled
from consensusml_tpu.compress.kernels import chunk_scatter
rows, chunk, k = 513, 512, 8
sv = jnp.asarray(rng.normal(size=(rows, k)), jnp.float32)
si = jnp.asarray(
    np.stack([rng.choice(chunk, size=k, replace=False) for _ in range(rows)]),
    jnp.int32,
)
acc = jnp.asarray(rng.normal(size=(rows, chunk)), jnp.float32)
got_sc = chunk_scatter(sv, si, chunk, acc, weight=0.25)
want_sc = np.asarray(acc).copy()
np.put_along_axis(
    want_sc,
    np.asarray(si),
    np.take_along_axis(np.asarray(acc), np.asarray(si), axis=1)
    + 0.25 * np.asarray(sv),
    axis=1,
)
out["scatter_exact"] = bool(
    np.allclose(np.asarray(got_sc), want_sc, atol=1e-6)
)
print(json.dumps(out))
"""


def test_pallas_kernels_match_reference_on_tpu():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {
        k: v
        for k, v in os.environ.items()
        if "xla_force_host_platform_device_count" not in v or k != "XLA_FLAGS"
    }
    proc = subprocess.run(
        [sys.executable, "-c", _CHILD],
        capture_output=True,
        text=True,
        timeout=900,
        cwd=repo,
        env=env,
    )
    assert proc.returncode == 0, proc.stderr[-1500:]
    line = [l for l in proc.stdout.splitlines() if l.startswith("{")][-1]
    result = json.loads(line)
    if "skip" in result:
        pytest.skip(result["skip"])
    assert result["quant_exact"], result
    assert result["scales_exact"], result
    assert result["dequant_exact"], result
    assert result["int4_pack_exact"], result
    assert result["int4_roundtrip_ok"], result
    assert result["topk_exact"], result


_FLASH_CHILD = r"""
import json
import numpy as np
import jax
import jax.numpy as jnp

if jax.default_backend() not in ("tpu", "axon"):
    print(json.dumps({"skip": f"no TPU (backend={jax.default_backend()})"}))
    raise SystemExit(0)

from consensusml_tpu.models.attention import dot_product_attention
from consensusml_tpu.models.flash_attention import flash_attention

out = {"backend": jax.default_backend()}
rng = np.random.default_rng(0)
b, s, h, d = 2, 1024, 4, 64
q, k, v = (jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32) for _ in range(3))
want = dot_product_attention(q, k, v, causal=True, dtype=jnp.float32, impl="dense")
got = flash_attention(q, k, v, causal=True, dtype=jnp.float32)
# default TPU matmul precision is bf16-class; both paths share it
out["fwd_max_err"] = float(jnp.max(jnp.abs(got - want)))
gf = jax.grad(lambda q: jnp.sum(flash_attention(q, k, v, causal=True, dtype=jnp.float32) ** 2))(q)
gd = jax.grad(lambda q: jnp.sum(dot_product_attention(q, k, v, causal=True, dtype=jnp.float32, impl="dense") ** 2))(q)
scale = float(jnp.max(jnp.abs(gd)))
out["dq_rel_err"] = float(jnp.max(jnp.abs(gf - gd))) / max(scale, 1e-9)

# per-key padding mask (the BERT path) — compiled, vs dense additive bias
kv_mask = jnp.asarray(np.stack([np.arange(s) < s, np.arange(s) < 700]), jnp.float32)
bias = jnp.where(kv_mask[:, None, None, :] > 0, 0.0, -1e30)
want_m = dot_product_attention(q, k, v, bias=bias, dtype=jnp.float32, impl="dense")
got_m = flash_attention(q, k, v, kv_mask=kv_mask, dtype=jnp.float32)
out["masked_fwd_max_err"] = float(jnp.max(jnp.abs(got_m - want_m)))
gm = jax.grad(lambda q: jnp.sum(flash_attention(q, k, v, kv_mask=kv_mask, dtype=jnp.float32) ** 2))(q)
gb = jax.grad(lambda q: jnp.sum(dot_product_attention(q, k, v, bias=bias, dtype=jnp.float32, impl="dense") ** 2))(q)
mscale = float(jnp.max(jnp.abs(gb)))
out["masked_dq_rel_err"] = float(jnp.max(jnp.abs(gm - gb))) / max(mscale, 1e-9)
print(json.dumps(out))
"""


def test_flash_attention_on_tpu():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "-c", _FLASH_CHILD],
        capture_output=True, text=True, timeout=900, cwd=repo,
    )
    assert proc.returncode == 0, proc.stderr[-1500:]
    line = [l for l in proc.stdout.splitlines() if l.startswith("{")][-1]
    result = json.loads(line)
    if "skip" in result:
        pytest.skip(result["skip"])
    assert result["fwd_max_err"] < 0.02, result  # bf16-precision matmuls
    assert result["dq_rel_err"] < 0.02, result
    assert result["masked_fwd_max_err"] < 0.02, result
    assert result["masked_dq_rel_err"] < 0.02, result


_FUSED_BN_CHILD = r"""
import json
import numpy as np
import jax
import jax.numpy as jnp

if jax.default_backend() not in ("tpu", "axon"):
    print(json.dumps({"skip": f"no TPU (backend={jax.default_backend()})"}))
    raise SystemExit(0)

from consensusml_tpu.models.fused_bn import fused_batch_norm

out = {"backend": jax.default_backend()}
rng = np.random.default_rng(0)
errs = {}
for name, (m, c) in {"wide": (4096, 256), "packed": (4096, 64)}.items():
    x = jnp.asarray(rng.normal(size=(m, c)), jnp.float32)
    gamma = jnp.asarray(rng.normal(size=(c,)) * 0.3 + 1.0, jnp.float32)
    beta = jnp.asarray(rng.normal(size=(c,)) * 0.1, jnp.float32)
    w = jnp.asarray(rng.normal(size=(c,)), jnp.float32)

    def loss(x, gamma, beta, impl):
        y, mean, var = fused_batch_norm(x, gamma, beta, act="relu", impl=impl)
        return jnp.sum(jnp.sin(y) * w)

    vg = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)), static_argnums=3)
    l_p, g_p = vg(x, gamma, beta, "pallas")
    l_j, g_j = vg(x, gamma, beta, "jnp")
    errs[name] = {
        "loss": abs(float(l_p - l_j)),
        "dx": float(jnp.max(jnp.abs(g_p[0] - g_j[0]))),
        "dgamma": float(jnp.max(jnp.abs(g_p[1] - g_j[1]))),
        "dbeta": float(jnp.max(jnp.abs(g_p[2] - g_j[2]))),
    }
out["errs"] = errs
print(json.dumps(out))
"""


def test_fused_bn_on_tpu():
    """The compiled fused-BN kernels match the jnp custom-VJP math on the
    chip (wide C>=128 and lane-packed C<128 variants, fwd + all grads)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "-c", _FUSED_BN_CHILD],
        capture_output=True, text=True, timeout=900, cwd=repo,
    )
    assert proc.returncode == 0, proc.stderr[-1500:]
    line = [l for l in proc.stdout.splitlines() if l.startswith("{")][-1]
    result = json.loads(line)
    if "skip" in result:
        pytest.skip(result["skip"])
    for name, e in result["errs"].items():
        assert e["loss"] < 1e-2 and e["dx"] < 1e-4, (name, e)
        assert e["dgamma"] < 1e-2 and e["dbeta"] < 1e-2, (name, e)

_FUSED_LN_CHILD = r"""
import json
import numpy as np
import jax
import jax.numpy as jnp

if jax.default_backend() not in ("tpu", "axon"):
    print(json.dumps({"skip": f"no TPU (backend={jax.default_backend()})"}))
    raise SystemExit(0)

from consensusml_tpu.models.fused_ln import fused_layer_norm

out = {"backend": jax.default_backend()}
rng = np.random.default_rng(0)
errs = {}
# gpt2-medium row shape and a bert-ish one
for name, (m, h) in {"gpt2": (4096, 1024), "bert": (2048, 256)}.items():
    x = jnp.asarray(rng.normal(size=(m, h)) * 2 + 0.5, jnp.bfloat16)
    gamma = jnp.asarray(rng.normal(size=(h,)) * 0.3 + 1.0, jnp.float32)
    beta = jnp.asarray(rng.normal(size=(h,)) * 0.1, jnp.float32)
    w = jnp.asarray(rng.normal(size=(m, h)), jnp.float32)

    def loss(x, gamma, beta, impl):
        y = fused_layer_norm(x, gamma, beta, 1e-6, jnp.float32, impl)
        return jnp.sum(jnp.sin(y) * w)

    vg = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)), static_argnums=3)
    l_p, g_p = vg(x, gamma, beta, "pallas")
    l_j, g_j = vg(x, gamma, beta, "jnp")
    errs[name] = {
        "loss": abs(float(l_p - l_j)),
        "dx": float(jnp.max(jnp.abs(jnp.asarray(g_p[0] - g_j[0], jnp.float32)))),
        "dgamma": float(jnp.max(jnp.abs(g_p[1] - g_j[1]))),
        "dbeta": float(jnp.max(jnp.abs(g_p[2] - g_j[2]))),
    }
out["errs"] = errs
print(json.dumps(out))
"""


def test_fused_ln_on_tpu():
    """The compiled fused-LN kernel matches the jnp custom-VJP math on
    the chip at the transformer row shapes (fwd + all grads); proves the
    Mosaic compile the interpreter tests cannot (cross-lane row
    reductions + revisited accumulator blocks)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "-c", _FUSED_LN_CHILD],
        capture_output=True, text=True, timeout=900, cwd=repo,
    )
    assert proc.returncode == 0, proc.stderr[-1500:]
    line = [l for l in proc.stdout.splitlines() if l.startswith("{")][-1]
    result = json.loads(line)
    if "skip" in result:
        pytest.skip(result["skip"])
    for name, e in result["errs"].items():
        assert e["loss"] < 2e-2 and e["dx"] < 1e-2, (name, e)
        assert e["dgamma"] < 5e-2 and e["dbeta"] < 5e-2, (name, e)
