"""Consensus engine tests: exact and CHOCO compressed gossip.

Key properties (SURVEY.md §7): identity-compressor CHOCO == plain gossip;
collective (shard_map/ppermute) == simulated (mixing matrix) for the
compressed path; compressed gossip contracts consensus error while
preserving the worker mean; payload on the wire is genuinely small.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from consensusml_tpu.comm import WorkerMesh, simulated
from consensusml_tpu.compress import IdentityCompressor, TopKCompressor, topk_int8_compressor
from consensusml_tpu.consensus import ConsensusEngine, GossipConfig
from consensusml_tpu.topology import DenseTopology, RingTopology, TorusTopology


def _params(topo, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "w": jnp.asarray(rng.normal(size=(topo.world_size, 8, 4)), jnp.float32),
        "b": jnp.asarray(rng.normal(size=(topo.world_size, 4)), jnp.float32),
    }


def _run_collective(engine, stacked, rounds):
    topo = engine.topology
    wmesh = WorkerMesh.create(topo, platform="cpu")
    blocked = jax.tree.map(
        lambda v: jax.device_put(
            v.reshape(*topo.mesh_shape, *v.shape[1:]), wmesh.worker_sharding()
        ),
        stacked,
    )

    @jax.jit
    @jax.shard_map(
        mesh=wmesh.mesh, in_specs=P(*topo.axis_names), out_specs=P(*topo.axis_names)
    )
    def run(tree):
        state = engine.init_state(tree)
        for _ in range(rounds):
            tree, state = engine.round_collective(tree, state)
        return tree

    out = run(blocked)
    return jax.tree.map(
        lambda v, ref: np.asarray(v).reshape(ref.shape), out, stacked
    )


def _run_simulated(engine, stacked, rounds):
    w = simulated.mixing_matrix(engine.topology)
    # fused CHOCO state is flat per worker: stacked init needs the count
    state = engine.init_state(
        stacked, world_size=engine.topology.world_size
    )
    for _ in range(rounds):
        stacked, state = engine.round_simulated(stacked, state, w)
    return jax.tree.map(np.asarray, stacked)


TOPOS = [RingTopology(8), TorusTopology(2, 4), DenseTopology(4)]


@pytest.mark.parametrize("topo", TOPOS, ids=lambda t: f"{t.name}{t.mesh_shape}")
def test_exact_engine_is_mixing(topo):
    engine = ConsensusEngine(GossipConfig(topology=topo))
    stacked = _params(topo)
    got = _run_collective(engine, stacked, rounds=1)
    w = topo.mixing_matrix()
    for key in stacked:
        flat = np.asarray(stacked[key]).reshape(topo.world_size, -1)
        np.testing.assert_allclose(
            got[key].reshape(topo.world_size, -1), w @ flat, rtol=1e-6, atol=1e-6
        )


def test_identity_choco_equals_plain_gossip():
    """CHOCO with Q=identity, gamma=1 reduces to x <- W x every round."""
    topo = RingTopology(8)
    engine = ConsensusEngine(
        GossipConfig(topology=topo, compressor=IdentityCompressor(), gamma=1.0)
    )
    stacked = _params(topo, seed=4)
    got = _run_simulated(engine, stacked, rounds=3)
    w = np.linalg.matrix_power(topo.mixing_matrix(), 3)
    for key in stacked:
        flat = np.asarray(stacked[key]).reshape(topo.world_size, -1)
        np.testing.assert_allclose(
            got[key].reshape(topo.world_size, -1), w @ flat, rtol=1e-5, atol=1e-5
        )


@pytest.mark.parametrize("topo", TOPOS, ids=lambda t: f"{t.name}{t.mesh_shape}")
def test_choco_collective_matches_simulated(topo):
    comp = TopKCompressor(ratio=0.25)
    engine = ConsensusEngine(GossipConfig(topology=topo, compressor=comp, gamma=0.5))
    stacked = _params(topo, seed=5)
    got_c = _run_collective(engine, stacked, rounds=4)
    got_s = _run_simulated(engine, stacked, rounds=4)
    for key in stacked:
        np.testing.assert_allclose(got_c[key], got_s[key], rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize(
    "comp,gamma",
    [
        (TopKCompressor(ratio=0.25), 0.4),
        (topk_int8_compressor(ratio=0.25, chunk=32), 0.4),
    ],
    ids=["topk", "topk+int8"],
)
def test_choco_contracts_and_preserves_mean(comp, gamma):
    topo = RingTopology(8)
    engine = ConsensusEngine(GossipConfig(topology=topo, compressor=comp, gamma=gamma))
    stacked = _params(topo, seed=6)
    mean_before = {k: np.asarray(v).mean(0) for k, v in stacked.items()}
    err0 = float(engine.consensus_error_simulated(stacked))

    w = simulated.mixing_matrix(topo)
    state = engine.init_state(stacked)
    x = stacked
    for _ in range(60):
        x, state = engine.round_simulated(x, state, w)
    err = float(engine.consensus_error_simulated(x))
    assert err < 0.15 * err0, f"consensus error {err} vs initial {err0}"
    for k in stacked:
        np.testing.assert_allclose(
            np.asarray(x[k]).mean(0), mean_before[k], atol=1e-4
        )


def test_fused_identity_choco_equals_plain_gossip():
    """fused_codec changes WHERE the codec runs (one concatenated vector),
    not the mixing math: with Q=identity, gamma=1 it is still x <- W x."""
    topo = RingTopology(8)
    engine = ConsensusEngine(
        GossipConfig(
            topology=topo, compressor=IdentityCompressor(), gamma=1.0,
            fused_codec=True,
        )
    )
    stacked = _params(topo, seed=4)
    got = _run_simulated(engine, stacked, rounds=3)
    w = np.linalg.matrix_power(topo.mixing_matrix(), 3)
    for key in stacked:
        flat = np.asarray(stacked[key]).reshape(topo.world_size, -1)
        np.testing.assert_allclose(
            got[key].reshape(topo.world_size, -1), w @ flat, rtol=1e-5, atol=1e-5
        )


@pytest.mark.parametrize("topo", TOPOS, ids=lambda t: f"{t.name}{t.mesh_shape}")
def test_fused_choco_collective_matches_simulated(topo):
    """Cross-backend parity with the codec running over the concatenated
    tree — both backends must flatten in the same leaf order."""
    comp = topk_int8_compressor(ratio=0.25, chunk=32)
    engine = ConsensusEngine(
        GossipConfig(topology=topo, compressor=comp, gamma=0.5, fused_codec=True)
    )
    stacked = _params(topo, seed=7)
    got_c = _run_collective(engine, stacked, rounds=4)
    got_s = _run_simulated(engine, stacked, rounds=4)
    for key in stacked:
        np.testing.assert_allclose(got_c[key], got_s[key], rtol=1e-5, atol=1e-5)


def test_fused_choco_contracts_and_preserves_mean():
    topo = RingTopology(8)
    engine = ConsensusEngine(
        GossipConfig(
            topology=topo,
            compressor=topk_int8_compressor(ratio=0.25, chunk=32),
            gamma=0.4,
            fused_codec=True,
        )
    )
    stacked = _params(topo, seed=6)
    mean_before = {k: np.asarray(v).mean(0) for k, v in stacked.items()}
    err0 = float(engine.consensus_error_simulated(stacked))
    w = simulated.mixing_matrix(topo)
    state = engine.init_state(stacked, world_size=topo.world_size)
    x = stacked
    for _ in range(60):
        x, state = engine.round_simulated(x, state, w)
    err = float(engine.consensus_error_simulated(x))
    assert err < 0.15 * err0, f"consensus error {err} vs initial {err0}"
    for k in stacked:
        np.testing.assert_allclose(
            np.asarray(x[k]).mean(0), mean_before[k], atol=1e-4
        )


def test_fused_codec_requires_compressor():
    with pytest.raises(NotImplementedError, match="nothing to fuse"):
        GossipConfig(topology=RingTopology(4), fused_codec=True)


def test_compressed_wire_is_small():
    """The payload that rides ppermute is ~25x smaller than dense (topk 1%
    of f32 + int8 values + i32 indices)."""
    comp = TopKCompressor(ratio=0.01)
    dense = 1_000_000 * 4
    assert comp.wire_bytes((1000, 1000), jnp.float32) <= dense / 12


def test_wire_bytes_per_round_accounting():
    """Bandwidth accounting: codec payloads vs dense, per-shift sends."""
    import numpy as np

    from consensusml_tpu.compress import topk_int8_compressor
    from consensusml_tpu.topology import (
        DenseTopology,
        OnePeerExponentialTopology,
        RingTopology,
    )

    params = {"w": jnp.zeros((100, 100)), "b": jnp.zeros((100,))}
    dense_bytes = (100 * 100 + 100) * 4

    # exact ring: dense payload x 2 shifts
    eng = ConsensusEngine(GossipConfig(topology=RingTopology(8)))
    assert eng.wire_bytes_per_round(params) == dense_bytes * 2
    # dense topology: one all-reduce pass
    eng = ConsensusEngine(GossipConfig(topology=DenseTopology(4)))
    assert eng.wire_bytes_per_round(params) == dense_bytes
    # compressed: payload well under dense
    comp = topk_int8_compressor(ratio=0.01, chunk=128)
    eng = ConsensusEngine(
        GossipConfig(topology=RingTopology(8), compressor=comp, gamma=0.5)
    )
    compressed = eng.wire_bytes_per_round(params)
    assert compressed < dense_bytes // 5
    assert compressed == 2 * sum(
        comp.wire_bytes(x.shape, jnp.float32) for x in params.values()
    )
    # one-peer time-varying: single send per round on average
    eng = ConsensusEngine(GossipConfig(topology=OnePeerExponentialTopology(8)))
    assert eng.wire_bytes_per_round(params) == dense_bytes
    # push-sum adds the mass scalar
    eng = ConsensusEngine(GossipConfig(topology=RingTopology(8), push_sum=True))
    assert eng.wire_bytes_per_round(params) == dense_bytes * 2 + 8


def test_compress_filter_mixes_model_state_exactly():
    """The "auto" compress filter: params ride CHOCO, the model_state
    subtree (BN running statistics) mixes EXACTLY — sparse delta codecs
    destroy running stats (measured: ResNet-50 study top-1 0.13 vs 0.80).
    """
    topo = RingTopology(8)
    engine = ConsensusEngine(
        GossipConfig(
            topology=topo,
            compressor=topk_int8_compressor(ratio=0.1, chunk=32),
            gamma=0.5,
        )
    )
    rng = np.random.default_rng(12)
    tree = {
        "params": {
            "w": jnp.asarray(rng.normal(size=(8, 16, 8)), jnp.float32)
        },
        "model_state": {
            "batch_stats": {
                "var": jnp.asarray(
                    1.0 + 0.1 * rng.random(size=(8, 32)), jnp.float32
                )
            }
        },
    }
    w = simulated.mixing_matrix(topo)
    state = engine.init_state(tree, world_size=8)
    # CHOCO state exists for params only: one leaf, shaped like w
    assert len(jax.tree.leaves(state.xhat)) == 1
    out, _ = engine.round_simulated(tree, state, w)
    # stats after ONE round equal exact mixing (no compression error)
    want = simulated.mix_stacked(tree["model_state"]["batch_stats"]["var"], w)
    np.testing.assert_allclose(
        np.asarray(out["model_state"]["batch_stats"]["var"]),
        np.asarray(want), rtol=1e-6, atol=1e-6,
    )
    # params went through the codec: NOT equal to exact mixing
    wmix = simulated.mix_stacked(tree["params"]["w"], w)
    assert float(jnp.max(jnp.abs(out["params"]["w"] - wmix))) > 1e-4
    # and variances stayed positive (the failure mode this guards)
    assert float(jnp.min(out["model_state"]["batch_stats"]["var"])) > 0


def test_compress_filter_none_compresses_everything():
    """compress_filter=None restores the old everything-compressed
    behavior, and raw trees without model_state are untouched by auto."""
    topo = RingTopology(4)
    rng = np.random.default_rng(13)
    tree = {
        "params": {"w": jnp.asarray(rng.normal(size=(4, 8, 8)), jnp.float32)},
        "model_state": {
            "m": jnp.asarray(rng.normal(size=(4, 16)), jnp.float32)
        },
    }
    w = simulated.mixing_matrix(topo)
    comp = topk_int8_compressor(ratio=0.5, chunk=32)
    eng_none = ConsensusEngine(
        GossipConfig(
            topology=topo, compressor=comp, gamma=0.5, compress_filter=None
        )
    )
    st = eng_none.init_state(tree, world_size=4)
    # state spans BOTH subtrees when the filter is off
    assert len(jax.tree.leaves(st.xhat)) == 2
    out, _ = eng_none.round_simulated(tree, st, w)
    mixed = simulated.mix_stacked(tree["model_state"]["m"], w)
    assert float(jnp.max(jnp.abs(out["model_state"]["m"] - mixed))) > 1e-5


def test_compress_filter_cross_backend_parity():
    """Collective == simulated with the split active (BN-style tree)."""
    topo = RingTopology(8)
    engine = ConsensusEngine(
        GossipConfig(
            topology=topo,
            compressor=TopKCompressor(ratio=0.25),
            gamma=0.5,
        )
    )
    rng = np.random.default_rng(14)
    stacked = {
        "params": {"w": jnp.asarray(rng.normal(size=(8, 8, 4)), jnp.float32)},
        "model_state": {
            "s": jnp.asarray(rng.normal(size=(8, 8)), jnp.float32)
        },
    }
    got_c = _run_collective(engine, stacked, rounds=3)
    got_s = _run_simulated(engine, stacked, rounds=3)
    for leaf_c, leaf_s in zip(jax.tree.leaves(got_c), jax.tree.leaves(got_s)):
        np.testing.assert_allclose(leaf_c, leaf_s, rtol=1e-5, atol=1e-5)


def test_compress_filter_composes_with_path_filter():
    """path_filter (what gossips) and compress_filter (what compresses)
    both act on ORIGINAL paths: a two-stage filter would silently lose
    the model_state exclusion once paths became flat-list indices."""
    topo = RingTopology(4)
    engine = ConsensusEngine(
        GossipConfig(
            topology=topo,
            compressor=topk_int8_compressor(ratio=0.25, chunk=32),
            gamma=0.5,
            # gossip everything except the frozen subtree
            path_filter=lambda p: getattr(p[-1], "key", None) != "frozen",
        )
    )
    rng = np.random.default_rng(15)
    tree = {
        "params": {
            "w": jnp.asarray(rng.normal(size=(4, 8, 8)), jnp.float32),
            "frozen": jnp.asarray(rng.normal(size=(4, 8)), jnp.float32),
        },
        "model_state": {
            "var": jnp.asarray(1.0 + rng.random(size=(4, 32)), jnp.float32)
        },
    }
    w = simulated.mixing_matrix(topo)
    state = engine.init_state(tree, world_size=4)
    # CHOCO tracks ONLY params/w: not frozen (path_filter), not var (auto)
    assert len(jax.tree.leaves(state.xhat)) == 1
    out, _ = engine.round_simulated(tree, state, w)
    # frozen leaf passed through untouched
    np.testing.assert_array_equal(
        np.asarray(out["params"]["frozen"]), np.asarray(tree["params"]["frozen"])
    )
    # stats mixed EXACTLY despite the path_filter being present
    np.testing.assert_allclose(
        np.asarray(out["model_state"]["var"]),
        np.asarray(simulated.mix_stacked(tree["model_state"]["var"], w)),
        rtol=1e-6, atol=1e-6,
    )


def test_gossip_steps_multiplies_contraction():
    """T consensus iterations per round contract like T single rounds
    (exact mixing: x -> W^T x), cross-backend, and wire accounting
    multiplies by T."""
    import numpy as np

    from consensusml_tpu.comm.simulated import mixing_matrix
    from consensusml_tpu.compress import topk_int8_compressor

    world = 8
    topo = RingTopology(world)
    w = mixing_matrix(topo)
    rng = np.random.default_rng(0)
    x = {"a": jnp.asarray(rng.normal(size=(world, 64)), jnp.float32)}

    e1 = ConsensusEngine(GossipConfig(topology=topo))
    e3 = ConsensusEngine(GossipConfig(topology=topo, gossip_steps=3))
    y1, _ = e1.round_simulated(x, None, w)
    y111, _ = e1.round_simulated(y1, None, w)
    y111, _ = e1.round_simulated(y111, None, w)
    y3, _ = e3.round_simulated(x, None, w)
    np.testing.assert_allclose(
        np.asarray(y3["a"]), np.asarray(y111["a"]), rtol=1e-5, atol=1e-6
    )

    # CHOCO: T iterations contract consensus error strictly more than 1
    comp = topk_int8_compressor(ratio=0.25, chunk=32)
    err = lambda v: float(
        np.sqrt(np.mean(np.sum((v - v.mean(0)) ** 2, axis=-1)))
    )
    for steps, expect_better in [(1, None), (4, True)]:
        eng = ConsensusEngine(
            GossipConfig(topology=topo, compressor=comp, gamma=0.2,
                         gossip_steps=steps)
        )
        st = eng.init_state(x, world_size=world)
        v = dict(x)
        for _ in range(5):
            v, st = eng.round_simulated(v, st, w)
        e = err(np.asarray(v["a"]))
        if steps == 1:
            e_single = e
        else:
            assert e < 0.5 * e_single, (e, e_single)

    # wire accounting multiplies by T
    p = {"a": jnp.zeros((512,), jnp.float32)}
    w1 = ConsensusEngine(
        GossipConfig(topology=topo, compressor=comp, gamma=0.2)
    ).wire_bytes_per_round(p)
    w4 = ConsensusEngine(
        GossipConfig(topology=topo, compressor=comp, gamma=0.2, gossip_steps=4)
    ).wire_bytes_per_round(p)
    assert w4 == 4 * w1


def test_gossip_steps_collective_matches_simulated():
    """gossip_steps > 1 stays cross-validated between backends (CHOCO)."""
    topo = RingTopology(8)
    comp = topk_int8_compressor(ratio=0.25, chunk=32)
    engine = ConsensusEngine(
        GossipConfig(topology=topo, compressor=comp, gamma=0.3, gossip_steps=3)
    )
    stacked = _params(topo)
    got = _run_collective(engine, stacked, rounds=2)
    want = _run_simulated(engine, stacked, rounds=2)
    for key in stacked:
        np.testing.assert_allclose(got[key], want[key], rtol=2e-5, atol=1e-6)


def test_gossip_steps_stochastic_codec_backends_agree():
    """The PER-ITERATION rng fold (gossip_steps > 1 + stochastic codec)
    must draw identical randomness on both backends — the deterministic
    topk test above cannot catch a fold-convention divergence."""
    import functools

    from consensusml_tpu.compress import QSGD4Compressor

    topo = RingTopology(4)
    comp = QSGD4Compressor(chunk=32)
    assert comp.stochastic
    engine = ConsensusEngine(
        GossipConfig(topology=topo, compressor=comp, gamma=0.3, gossip_steps=3)
    )
    rng = np.random.default_rng(5)
    stacked = {
        "a": jnp.asarray(rng.normal(size=(4, 64)), jnp.float32),
    }
    keys = jax.random.split(jax.random.key(7), 4)

    # simulated
    st = engine.init_state(stacked, world_size=4)
    sim, _ = engine.round_simulated(stacked, st, simulated.mixing_matrix(topo), rng=keys)

    # collective
    wmesh = WorkerMesh.create(topo, platform="cpu")
    blocked = jax.tree.map(
        lambda v: jax.device_put(v, wmesh.stacked_sharding()), stacked
    )
    bkeys = jax.device_put(keys, wmesh.stacked_sharding())

    @jax.jit
    @functools.partial(
        jax.shard_map,
        mesh=wmesh.mesh,
        in_specs=(P(*topo.axis_names), P(*topo.axis_names)),
        out_specs=P(*topo.axis_names),
    )
    def run(tree, k):
        sq = lambda t: jax.tree.map(lambda v: v.reshape(v.shape[1:]), t)
        state = engine.init_state(sq(tree))
        out, _ = engine.round_collective(sq(tree), state, rng=sq({"k": k})["k"])
        return jax.tree.map(lambda v: v.reshape((1,) + v.shape), out)

    col = run(blocked, bkeys)
    np.testing.assert_allclose(
        np.asarray(col["a"]), np.asarray(sim["a"]), rtol=2e-5, atol=1e-6
    )


def test_codec_warmup_rounds():
    """Warmup rounds mix exactly (bit-equal to the exact engine) while
    warming xhat/s; post-warmup rounds run pure CHOCO with tracking
    already caught up — and the whole schedule stays cross-backend."""
    topo = RingTopology(8)
    comp = topk_int8_compressor(ratio=0.25, chunk=32)
    warm_engine = ConsensusEngine(
        GossipConfig(topology=topo, compressor=comp, gamma=0.3,
                     codec_warmup_rounds=2)
    )
    exact_engine = ConsensusEngine(GossipConfig(topology=topo))

    # warmup must track the exact engine at the SAME gossip_steps too
    w2 = simulated.mixing_matrix(topo)
    wg = ConsensusEngine(
        GossipConfig(topology=topo, compressor=comp, gamma=0.3,
                     codec_warmup_rounds=1, gossip_steps=2)
    )
    eg = ConsensusEngine(GossipConfig(topology=topo, gossip_steps=2))
    p0 = _params(topo, seed=9)
    stg = wg.init_state(p0, world_size=topo.world_size)
    warm_out, _ = wg.round_simulated(p0, stg, w2, step=jnp.int32(0))
    exact_out, _ = eg.round_simulated(p0, None, w2)
    for key in p0:
        np.testing.assert_allclose(
            np.asarray(warm_out[key]), np.asarray(exact_out[key]), rtol=1e-6
        )
    stacked = _params(topo)
    w = simulated.mixing_matrix(topo)

    # rounds 0-1 (warmup): params move EXACTLY like exact mixing
    st = warm_engine.init_state(stacked, world_size=topo.world_size)
    cur = stacked
    exact = stacked
    for step in range(2):
        cur, st = warm_engine.round_simulated(
            cur, st, w, step=jnp.int32(step)
        )
        exact, _ = exact_engine.round_simulated(exact, None, w)
        for key in stacked:
            np.testing.assert_allclose(
                np.asarray(cur[key]), np.asarray(exact[key]), rtol=1e-6
            )
    # tracking state warmed: xhat moved toward x (not still zero)
    assert float(jnp.abs(st.xhat["w"]).sum()) > 0

    # post-warmup: compressed rounds keep contracting disagreement
    err = lambda t: float(
        np.sqrt(np.mean(np.sum((np.asarray(t["w"]) - np.asarray(t["w"]).mean(0)) ** 2, axis=-1)))
    )
    e_before = err(cur)
    for step in range(2, 6):
        cur, st = warm_engine.round_simulated(cur, st, w, step=jnp.int32(step))
    assert err(cur) < e_before

    # cross-backend: the same schedule through the collective engine
    got = _run_collective_steps(warm_engine, stacked, rounds=4)
    st2 = warm_engine.init_state(stacked, world_size=topo.world_size)
    sim = stacked
    for step in range(4):
        sim, st2 = warm_engine.round_simulated(sim, st2, w, step=jnp.int32(step))
    for key in stacked:
        np.testing.assert_allclose(
            got[key], np.asarray(sim[key]), rtol=2e-5, atol=1e-6
        )


def _run_collective_steps(engine, stacked, rounds):
    """Like _run_collective but passing the round counter (warmup)."""
    import functools

    topo = engine.topology
    wmesh = WorkerMesh.create(topo, platform="cpu")
    blocked = jax.tree.map(
        lambda v: jax.device_put(
            v.reshape(*topo.mesh_shape, *v.shape[1:]), wmesh.worker_sharding()
        ),
        stacked,
    )

    @jax.jit
    @functools.partial(
        jax.shard_map,
        mesh=wmesh.mesh,
        in_specs=P(*topo.axis_names),
        out_specs=P(*topo.axis_names),
    )
    def run(tree):
        state = engine.init_state(tree)
        for step in range(rounds):
            tree, state = engine.round_collective(
                tree, state, step=jnp.int32(step)
            )
        return tree

    out = run(blocked)
    return jax.tree.map(
        lambda v, ref: np.asarray(v).reshape(ref.shape), out, stacked
    )


def test_codec_refresh_every():
    """Every K-th round runs the dense warmup-style round: bit-equal to
    exact mixing on refresh rounds, CHOCO between, cross-backend."""
    topo = RingTopology(8)
    comp = topk_int8_compressor(ratio=0.25, chunk=32)
    eng = ConsensusEngine(
        GossipConfig(topology=topo, compressor=comp, gamma=0.3,
                     codec_refresh_every=3)
    )
    exact = ConsensusEngine(GossipConfig(topology=topo))
    stacked = _params(topo, seed=11)
    w = simulated.mixing_matrix(topo)

    st = eng.init_state(stacked, world_size=topo.world_size)
    cur = stacked
    for step in range(6):
        prev = cur
        cur, st = eng.round_simulated(cur, st, w, step=jnp.int32(step))
        if step % 3 == 0:  # refresh rounds mix exactly
            ref, _ = exact.round_simulated(prev, None, w)
            for key in stacked:
                np.testing.assert_allclose(
                    np.asarray(cur[key]), np.asarray(ref[key]), rtol=1e-6
                )

    # cross-backend over the mixed schedule
    got = _run_collective_steps(eng, stacked, rounds=5)
    st2 = eng.init_state(stacked, world_size=topo.world_size)
    sim = stacked
    for step in range(5):
        sim, st2 = eng.round_simulated(sim, st2, w, step=jnp.int32(step))
    for key in stacked:
        np.testing.assert_allclose(
            got[key], np.asarray(sim[key]), rtol=2e-5, atol=1e-6
        )
