"""Cross-validation: collective gossip (shard_map + ppermute) vs the
simulated mixing-matrix oracle, on a virtual 8-device CPU mesh.

This is the core correctness property of the framework: both backends must
apply the SAME mixing operator for every topology, so decentralized runs
are reproducible across the CPU-reference and TPU-collective paths
(reference parity: SURVEY.md L1/L3/L7 — NCCL backend vs CPU simulator).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from consensusml_tpu.comm import WorkerMesh, collectives, simulated
from consensusml_tpu.topology import (
    DenseTopology,
    RingTopology,
    TorusTopology,
)

TOPOLOGIES = [
    RingTopology(8),
    RingTopology(4),
    RingTopology(2),
    TorusTopology(2, 4),
    TorusTopology(2, 2),
    DenseTopology(8),
    DenseTopology(4),
]


def _mesh(topo):
    return WorkerMesh.create(topo, platform="cpu")


def _stacked(topo, shape=(5, 3), seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.normal(size=(topo.world_size, *shape)), jnp.float32)


def _collective_mix(wmesh, x_flat):
    """Run one collective mix round on flat-stacked input, return flat."""
    topo = wmesh.topology
    x = x_flat.reshape(*topo.mesh_shape, *x_flat.shape[1:])

    @jax.jit
    @jax.shard_map(
        mesh=wmesh.mesh,
        in_specs=P(*topo.axis_names),
        out_specs=P(*topo.axis_names),
    )
    def step(block):
        return collectives.mix(block, topo)

    out = step(jax.device_put(x, wmesh.worker_sharding()))
    return np.asarray(out).reshape(x_flat.shape)


@pytest.mark.parametrize("topo", TOPOLOGIES, ids=lambda t: f"{t.name}{t.mesh_shape}")
def test_collective_matches_simulated(topo):
    x = _stacked(topo)
    w = simulated.mixing_matrix(topo)
    expected = np.asarray(simulated.mix_stacked(x, w))
    got = _collective_mix(_mesh(topo), x)
    np.testing.assert_allclose(got, expected, rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("topo", TOPOLOGIES, ids=lambda t: f"{t.name}{t.mesh_shape}")
def test_collective_matches_mixing_matrix(topo):
    """Collective mix == W @ x with the numpy mixing matrix directly."""
    x = _stacked(topo, shape=(6,))
    expected = topo.mixing_matrix() @ np.asarray(x)
    got = _collective_mix(_mesh(topo), x)
    np.testing.assert_allclose(got, expected, rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("topo", TOPOLOGIES, ids=lambda t: f"{t.name}{t.mesh_shape}")
def test_consensus_error_matches(topo):
    x = _stacked(topo, shape=(4, 2), seed=3)
    tree = {"a": x, "b": 2.0 * x[:, :1, 0]}
    expected = float(simulated.consensus_error_stacked(tree, topo.world_size))

    wmesh = _mesh(topo)
    blocked = jax.tree.map(
        lambda v: jax.device_put(
            v.reshape(*topo.mesh_shape, *v.shape[1:]), wmesh.worker_sharding()
        ),
        tree,
    )

    @jax.jit
    @jax.shard_map(
        mesh=wmesh.mesh, in_specs=P(*topo.axis_names), out_specs=P()
    )
    def err(block_tree):
        return collectives.consensus_error(block_tree, topo)

    got = float(err(blocked))
    assert got == pytest.approx(expected, rel=1e-5)
    # sanity: hand-computed RMS deviation
    manual = 0.0
    for leaf in [np.asarray(tree["a"]), np.asarray(tree["b"])]:
        flat = leaf.reshape(topo.world_size, -1)
        dev = flat - flat.mean(0, keepdims=True)
        manual += (dev**2).sum() / topo.world_size
    assert got == pytest.approx(float(np.sqrt(manual)), rel=1e-5)


def test_repeated_mixing_converges_to_mean():
    topo = RingTopology(8)
    wmesh = _mesh(topo)
    x = _stacked(topo, shape=(3,), seed=7)
    target = np.asarray(x).mean(0)

    @jax.jit
    @jax.shard_map(
        mesh=wmesh.mesh, in_specs=P(*topo.axis_names), out_specs=P(*topo.axis_names)
    )
    def many_rounds(block):
        def body(_, v):
            return collectives.mix(v, topo)

        return jax.lax.fori_loop(0, 200, body, block)

    out = np.asarray(many_rounds(jax.device_put(x.reshape(8, 1, 3), wmesh.worker_sharding())))
    np.testing.assert_allclose(out.reshape(8, 3), np.tile(target, (8, 1)), atol=1e-4)
    # mean preserved exactly (doubly stochastic)
    np.testing.assert_allclose(out.reshape(8, 3).mean(0), target, atol=1e-5)


def test_ppermute_shift_direction():
    """offset=+1 receives from rank-1 (left neighbor): data rotates right."""
    topo = RingTopology(8)
    wmesh = _mesh(topo)
    x = jnp.arange(8.0)

    @jax.jit
    @jax.shard_map(mesh=wmesh.mesh, in_specs=P("workers"), out_specs=P("workers"))
    def shift(v):
        return collectives.ppermute_shift(v, topo, topo.shifts[0])

    out = np.asarray(shift(jax.device_put(x, wmesh.worker_sharding())))
    assert topo.shifts[0].offset == 1
    np.testing.assert_array_equal(out, np.roll(np.arange(8.0), 1))


def test_mesh_too_few_devices():
    with pytest.raises(RuntimeError, match="need 16 devices"):
        WorkerMesh.create(RingTopology(16), platform="cpu")


def test_bf16_mixing_accumulates_in_f32():
    """bf16 params survive many mixing rounds without drifting off the mean."""
    topo = RingTopology(8)
    w = simulated.mixing_matrix(topo)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(8, 64)), jnp.bfloat16)
    mean_before = np.asarray(x, np.float32).mean(0)
    y = x
    for _ in range(50):
        y = simulated.mix_stacked(y, w)
    assert y.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(y, np.float32).mean(0), mean_before, atol=0.05
    )
