"""CLI entry-point tests (reference L6: train.py / worker.py / --device)."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(args, timeout=600):
    return subprocess.run(
        [sys.executable, *args],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=timeout,
        env={**os.environ, "JAX_PLATFORMS": ""},
    )


def test_train_list():
    r = _run(["train.py", "--list"])
    assert r.returncode == 0, r.stderr
    for name in ("mnist_mlp", "cifar_resnet50", "bert_mlm", "llama_lora", "gpt2_topk"):
        assert name in r.stdout


def test_train_requires_config():
    r = _run(["train.py"])
    assert r.returncode == 2
    assert "--config" in r.stderr


def test_train_unknown_config():
    r = _run(["train.py", "--config", "nope", "--device", "cpu"])
    assert r.returncode != 0
    assert "unknown config" in r.stderr


def test_train_mnist_end_to_end(tmp_path):
    metrics = tmp_path / "m.jsonl"
    r = _run(
        [
            "train.py", "--config", "mnist_mlp", "--device", "cpu",
            "--rounds", "5", "--metrics-out", str(metrics),
        ]
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "final: loss=" in r.stdout
    lines = [json.loads(l) for l in metrics.read_text().splitlines()]
    assert len(lines) == 5
    assert lines[-1]["loss"] < lines[0]["loss"]
    assert "consensus_error" in lines[0]


def test_train_checkpoint_resume(tmp_path):
    ck = tmp_path / "ckpt"
    r1 = _run(
        ["train.py", "--config", "mnist_mlp", "--device", "cpu", "--rounds", "3",
         "--checkpoint-dir", str(ck)]
    )
    assert r1.returncode == 0, r1.stderr[-2000:]
    assert (ck / "step_3").exists()
    r2 = _run(
        ["train.py", "--config", "mnist_mlp", "--device", "cpu", "--rounds", "2",
         "--resume", str(ck / "step_3")]
    )
    assert r2.returncode == 0, r2.stderr[-2000:]
    assert "resumed from" in r2.stdout


def test_worker_single_process_forwards():
    r = _run(
        ["worker.py", "--num-processes", "1", "--",
         "--config", "mnist_mlp", "--device", "cpu", "--rounds", "2"]
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "final: loss=" in r.stdout


def test_train_llama_lora_model_axes_tp2():
    """Hybrid gossip-DP x tensor-parallel reachable from the CLI: 2x2
    torus of workers, each a tp=2 submesh (8 virtual devices total)."""
    r = _run(
        ["train.py", "--config", "llama_lora", "--device", "cpu",
         "--rounds", "2", "--model-axes", "tp=2"],
        timeout=900,
    )
    assert r.returncode == 0, r.stderr[-800:]
    assert "model_axes=tp=2" in r.stdout
    assert "final:" in r.stdout


def test_train_model_axes_rejected_without_rules():
    r = _run(
        ["train.py", "--config", "mnist_mlp", "--device", "cpu",
         "--rounds", "1", "--model-axes", "tp=2"],
    )
    assert r.returncode == 2
    assert "no model-sharding rules" in r.stderr


def test_train_model_axes_bad_syntax():
    r = _run(
        ["train.py", "--config", "llama_lora", "--device", "cpu",
         "--rounds", "1", "--model-axes", "tp-two"],
    )
    assert r.returncode == 2
    assert "bad --model-axes" in r.stderr


def test_train_model_axes_multi_axis_rejected():
    r = _run(
        ["train.py", "--config", "llama_lora", "--device", "cpu",
         "--rounds", "1", "--model-axes", "tp=2,ep=2"],
    )
    assert r.returncode == 2
    assert "single axis" in r.stderr


def test_train_model_axes_zero_rejected():
    r = _run(
        ["train.py", "--config", "llama_lora", "--device", "cpu",
         "--rounds", "1", "--model-axes", "tp=0"],
    )
    assert r.returncode == 2
    assert "sizes must be" in r.stderr


def test_train_topology_override_hierarchical():
    r = _run(
        ["train.py", "--config", "cifar_resnet50", "--device", "cpu",
         "--rounds", "2", "--topology", "hierarchical:slices=2,outer_every=2"],
        timeout=900,
    )
    assert r.returncode == 0, r.stderr[-800:]
    assert "final:" in r.stdout


def test_train_topology_override_bad_name():
    r = _run(
        ["train.py", "--config", "mnist_mlp", "--device", "cpu",
         "--rounds", "1", "--topology", "bogus"],
    )
    assert r.returncode == 2
    assert "bad --topology" in r.stderr


def test_async_saver_unit(tmp_path):
    """AsyncSaver writes usable checkpoints and surfaces write errors."""
    import jax
    import jax.numpy as jnp

    from consensusml_tpu.utils import AsyncSaver, restore_state

    saver = AsyncSaver()
    state = {"a": jnp.arange(4.0), "b": jnp.ones((2, 2))}
    saver.submit(str(tmp_path / "ck"), state, step=1)
    saver.wait()
    got = restore_state(saver.last_path, jax.tree.map(jnp.zeros_like, state))
    for k in state:
        assert (got[k] == state[k]).all()
    # a failing write raises on wait, not silently
    saver.submit("/proc/definitely/not/writable", state)
    import pytest as _pytest

    with _pytest.raises(RuntimeError, match="async checkpoint"):
        saver.wait()


def test_train_native_loader():
    """--native-loader trains end-to-end through the C++ prefetch ring."""
    from consensusml_tpu import native

    if not native.available():
        pytest.skip("native library not buildable here")
    r = _run(
        ["train.py", "--config", "mnist_mlp", "--device", "cpu",
         "--rounds", "3", "--native-loader"],
    )
    assert r.returncode == 0, r.stderr[-800:]
    assert "final:" in r.stdout


def test_train_native_loader_with_data_dir(tmp_path):
    from consensusml_tpu import native

    if not native.available():
        pytest.skip("native library not buildable here")
    from tests.test_files_data import make_mnist_dir

    make_mnist_dir(str(tmp_path / "m"), n_train=256)
    r = _run(
        ["train.py", "--config", "mnist_mlp", "--device", "cpu",
         "--rounds", "2", "--native-loader", "--data-dir", str(tmp_path / "m")],
    )
    assert r.returncode == 0, r.stderr[-800:]
    assert "final:" in r.stdout


def test_train_native_wire_u8(tmp_path):
    """--native-wire u8 ships quantized file bytes and dequants inside
    the jitted step: training runs and the loss falls; non-image configs
    and u8-without-native-loader fail fast with diagnostics."""
    from consensusml_tpu import native

    if not native.available():
        pytest.skip("native library not buildable here")
    from tests.test_files_data import make_mnist_dir

    make_mnist_dir(str(tmp_path / "m"), n_train=256)
    metrics = tmp_path / "u8.jsonl"
    r = _run(
        ["train.py", "--config", "mnist_mlp", "--device", "cpu",
         "--rounds", "6", "--native-loader", "--native-wire", "u8",
         "--data-dir", str(tmp_path / "m"), "--metrics-out", str(metrics)],
    )
    assert r.returncode == 0, r.stderr[-800:]
    lines = [json.loads(l) for l in metrics.read_text().splitlines()]
    assert lines[-1]["loss"] < lines[0]["loss"]

    r = _run(["train.py", "--config", "mnist_mlp", "--device", "cpu",
              "--rounds", "2", "--native-wire", "u8"])
    assert r.returncode == 2 and "requires --native-loader" in r.stderr
    r = _run(["train.py", "--config", "bert_mlm", "--device", "cpu",
              "--rounds", "2", "--native-loader", "--native-wire", "u8"])
    assert r.returncode == 2 and "no u8-wire native path" in r.stderr


def test_train_native_wire_u8_checkpoint_resume(tmp_path):
    """u8 wire composes with checkpoint/resume: the resumed run re-binds
    the u8 source at the recorded round offset (start_seq keeps the
    byte stream exact) and keeps training."""
    from consensusml_tpu import native

    if not native.available():
        pytest.skip("native library not buildable here")
    from tests.test_files_data import make_mnist_dir

    make_mnist_dir(str(tmp_path / "m"), n_train=256)
    ck = tmp_path / "ckpt"
    base = ["train.py", "--config", "mnist_mlp", "--device", "cpu",
            "--native-loader", "--native-wire", "u8",
            "--data-dir", str(tmp_path / "m")]
    r1 = _run(base + ["--rounds", "3", "--checkpoint-dir", str(ck)])
    assert r1.returncode == 0, r1.stderr[-800:]
    r2 = _run(base + ["--rounds", "2", "--resume", str(ck / "step_3")])
    assert r2.returncode == 0, r2.stderr[-800:]
    assert "resumed from" in r2.stdout and "final:" in r2.stdout


def test_train_lr_schedule_flags(tmp_path):
    """--lr/--lr-schedule/--warmup-rounds/--grad-clip rebuild the config
    optimizer and still train (loss must improve under warmup+cosine)."""
    metrics = tmp_path / "m.jsonl"
    r = _run(
        [
            "train.py", "--config", "mnist_mlp", "--device", "cpu",
            "--rounds", "6", "--lr", "2e-3", "--lr-schedule", "cosine",
            "--warmup-rounds", "2", "--grad-clip", "1.0",
            "--metrics-out", str(metrics),
        ]
    )
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [json.loads(l) for l in metrics.read_text().splitlines()]
    assert lines[-1]["loss"] < lines[0]["loss"]


def test_train_codec_override(tmp_path):
    """--codec swaps the compressed-gossip codec (and is rejected on
    exact-mixing configs)."""
    r = _run(
        ["train.py", "--config", "gpt2_topk", "--device", "cpu",
         "--rounds", "2", "--codec", "topk_int4"],
    )
    assert r.returncode == 0, r.stderr[-1500:]
    assert "final: loss=" in r.stdout
    bad = _run(
        ["train.py", "--config", "mnist_mlp", "--device", "cpu",
         "--rounds", "1", "--codec", "topk_int4"],
    )
    assert bad.returncode == 2
    assert "exact mixing" in bad.stderr


def test_train_eval_every(tmp_path):
    """--eval-every K runs the held-out eval during training."""
    r = _run(
        ["train.py", "--config", "mnist_mlp", "--device", "cpu",
         "--rounds", "5", "--eval-batches", "2", "--eval-every", "2"],
    )
    assert r.returncode == 0, r.stderr[-1500:]
    assert "[round 1] eval[mean-model]" in r.stdout
    assert "[round 3] eval[mean-model]" in r.stdout
    # final round is NOT an eval-every boundary here; the end-of-run
    # eval still runs untagged (and is never duplicated on boundaries)
    assert "\neval[mean-model]" in r.stdout
    assert r.stdout.count("eval[mean-model]") == 3
    bad = _run(
        ["train.py", "--config", "mnist_mlp", "--device", "cpu",
         "--rounds", "1", "--eval-every", "2"],
    )
    assert bad.returncode == 2 and "--eval-batches" in bad.stderr


def test_train_gossip_steps_and_gamma():
    r = _run(
        [
            "train.py", "--config", "gpt2_topk", "--device", "cpu",
            "--backend", "simulated", "--rounds", "3",
            "--gossip-steps", "2", "--gamma", "0.2",
        ]
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "final: loss=" in r.stdout


def test_train_gamma_rejected_on_exact_config():
    r = _run(["train.py", "--config", "mnist_mlp", "--device", "cpu",
              "--gamma", "0.3", "--rounds", "2"])
    assert r.returncode == 2
    assert "--gamma" in r.stderr
