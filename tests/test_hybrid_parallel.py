"""Hybrid gossip-DP x tensor-parallel workers (partial-manual shard_map).

The 8 virtual CPU devices become a (workers..., tp) mesh: gossip
collectives run manually over the worker axes while the model axes stay
in XLA auto mode, sharded by the regex rules in
consensusml_tpu.parallel.sharding. Correctness oracle: the simulated
(one-device, mixing-matrix) backend must produce the same trajectory.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from consensusml_tpu.comm import WorkerMesh
from consensusml_tpu.consensus import GossipConfig
from consensusml_tpu.data import SyntheticLM, lm_round_batches
from consensusml_tpu.parallel import gpt2_tp_rules, llama_tp_rules, spec_for_path
from consensusml_tpu.topology import RingTopology, TorusTopology
from consensusml_tpu.train import (
    LocalSGDConfig,
    init_stacked_state,
    make_collective_train_step,
    make_simulated_train_step,
)


def test_spec_for_path_rules():
    rules = [(r"q_proj/base/kernel", (None, "tp")), (r"down_proj", ("tp", None))]
    assert spec_for_path("layer_0/q_proj/base/kernel", 2, rules) == (None, "tp")
    assert spec_for_path("layer_3/down_proj/kernel", 2, rules) == ("tp", None)
    assert spec_for_path("final_norm/scale", 1, rules) == (None,)
    assert spec_for_path("anything", 2, None) == (None, None)
    with pytest.raises(ValueError, match="only"):
        spec_for_path("layer_0/q_proj/base/kernel", 1, rules)


def _llama_bundle(world):
    from consensusml_tpu.models.llama import llama_tiny, llama_loss_fn

    # f32 compute: in bf16 the tp-split matmul reduction order shifts
    # partial sums enough that Adam amplifies it past any useful tolerance
    model = llama_tiny(lora_rank=4, dtype=jnp.float32)
    # sgd, not adam: adam's g/sqrt(v) normalization turns float-noise on
    # near-zero grads into lr-sized param flips, which would force a
    # uselessly loose tolerance; sgd keeps the oracle comparison tight
    cfg = LocalSGDConfig(
        gossip=GossipConfig(topology=RingTopology(world) if world != 4 else TorusTopology(2, 2)),
        optimizer=optax.sgd(0.05, momentum=0.9),
        h=2,
    )
    seq = 16
    init = lambda r: model.init(r, jnp.zeros((1, seq), jnp.int32))["params"]
    data = SyntheticLM(vocab_size=256, seq_len=seq)
    batches = lambda rounds, seed: lm_round_batches(data, world, cfg.h, 4, rounds, seed)
    return model, cfg, init, llama_loss_fn(model), batches


@pytest.mark.parametrize("model_axes", [(("tp", 2),), (("tp", 4),)])
def test_llama_tp_matches_simulated(model_axes):
    """Torus gossip workers x tp submesh == simulated mixing-matrix oracle."""
    per_worker = int(np.prod([s for _, s in model_axes]))
    world = 8 // per_worker
    model, cfg, init, loss_fn, batches = _llama_bundle(world)

    wmesh = WorkerMesh.create(
        cfg.gossip.topology, devices=jax.devices()[:8], model_axes=model_axes
    )
    assert wmesh.manual_axes() == frozenset(cfg.gossip.topology.axis_names)

    state_c = init_stacked_state(cfg, init, jax.random.key(0), world)
    state_c = wmesh.shard_stacked(state_c, rules=llama_tp_rules("tp"))
    # params really are split over tp
    kernel = state_c.params["layer_0"]["q_proj"]["base"]["kernel"]
    tp_shard = kernel.sharding.spec[-1]
    assert tp_shard == "tp", f"expected tp-sharded qkv kernel, got {kernel.sharding}"

    step_c = make_collective_train_step(cfg, loss_fn, wmesh)
    step_s = make_simulated_train_step(cfg, loss_fn)
    state_s = init_stacked_state(cfg, init, jax.random.key(0), world)

    for batch in batches(2, seed=0):
        batch_c = wmesh.shard_stacked(batch)
        state_c, m_c = step_c(state_c, batch_c)
        state_s, m_s = step_s(state_s, batch)

    # TP collectives change accumulation order -> small float drift
    np.testing.assert_allclose(
        float(m_c["loss"]), float(m_s["loss"]), rtol=1e-3, atol=1e-4
    )
    np.testing.assert_allclose(
        float(m_c["consensus_error"]),
        float(m_s["consensus_error"]),
        rtol=2e-3,
        atol=1e-5,
    )
    # Adam turns collective-accumulation float noise into ~1e-3 param drift
    # after a couple of rounds; a real gossip/sharding bug is orders larger.
    for a, b in zip(jax.tree.leaves(state_c.params), jax.tree.leaves(state_s.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-4)


def test_gpt2_tp_rules_apply():
    """GPT-2 rule set matches its fused-qkv parameter layout."""
    from consensusml_tpu.models.gpt2 import GPT2Config, GPT2LM

    model = GPT2LM(
        config=GPT2Config(vocab_size=64, hidden=32, layers=1, heads=2, max_len=16, dropout=0.0)
    )
    params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    topo = RingTopology(4)
    wmesh = WorkerMesh.create(topo, devices=jax.devices()[:8], model_axes=(("tp", 2),))
    stacked = jax.tree.map(lambda x: jnp.stack([x] * 4), params)
    shardings = wmesh.stacked_shardings(stacked, rules=gpt2_tp_rules("tp"))
    flat = {
        jax.tree_util.keystr(p, simple=True, separator="/"): s.spec
        for p, s in jax.tree_util.tree_flatten_with_path(shardings)[0]
    }
    assert flat["h_0/qkv/kernel"][2] == "tp"
    assert flat["h_0/out/kernel"][1] == "tp"
    assert flat["h_0/mlp_in/kernel"][2] == "tp"
    assert flat["wte/embedding"][2] == "tp"
    assert flat["ln_f/scale"] == jax.sharding.PartitionSpec(("workers",), None)
