"""SlowMo outer optimizer: reduction to plain gossip, backend agreement,
and convergence on top of local-SGD.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from consensusml_tpu.comm import WorkerMesh
from consensusml_tpu.consensus import GossipConfig
from consensusml_tpu.data import SyntheticClassification, round_batches
from consensusml_tpu.models import MLP, mlp_loss_fn
from consensusml_tpu.topology import RingTopology
from consensusml_tpu.train import (
    LocalSGDConfig,
    SlowMoConfig,
    init_stacked_state,
    make_collective_train_step,
    make_simulated_train_step,
    slowmo_init,
    slowmo_update,
)


def _setup(topo, outer, h=2, lr=1e-2):
    model = MLP(hidden=16)
    cfg = LocalSGDConfig(
        gossip=GossipConfig(topology=topo),
        optimizer=optax.sgd(lr, momentum=0.9),
        h=h,
        outer=outer,
    )
    init = lambda rng: model.init(rng, jnp.zeros((1, 28, 28, 1)))["params"]
    return model, cfg, init


def test_config_validation():
    with pytest.raises(ValueError):
        SlowMoConfig(beta=1.0)
    with pytest.raises(ValueError):
        SlowMoConfig(beta=-0.1)
    with pytest.raises(ValueError):
        SlowMoConfig(alpha=0.0)


def test_beta0_alpha1_reduces_to_plain_gossip():
    """SlowMo(beta=0, alpha=1) must reproduce the base round EXACTLY."""
    topo = RingTopology(4)
    data = SyntheticClassification(n=512)

    def run(outer):
        model, cfg, init = _setup(topo, outer)
        step = make_simulated_train_step(cfg, mlp_loss_fn(model))
        state = init_stacked_state(cfg, init, jax.random.key(0), topo.world_size)
        for batch in round_batches(data, topo.world_size, h=2, batch=16, rounds=4):
            state, m = step(state, batch)
        return state, m

    base_state, base_m = run(None)
    slow_state, slow_m = run(SlowMoConfig(beta=0.0, alpha=1.0))
    assert float(base_m["loss"]) == pytest.approx(float(slow_m["loss"]), rel=1e-6)
    for a, b in zip(
        jax.tree.leaves(base_state.params), jax.tree.leaves(slow_state.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_collective_matches_simulated_slowmo():
    topo = RingTopology(4)
    model, cfg, init = _setup(topo, SlowMoConfig(beta=0.8))
    data = SyntheticClassification(n=512)
    loss_fn = mlp_loss_fn(model)
    sim_step = make_simulated_train_step(cfg, loss_fn)
    wmesh = WorkerMesh.create(topo, platform="cpu")
    col_step = make_collective_train_step(cfg, loss_fn, wmesh)
    state = init_stacked_state(cfg, init, jax.random.key(4), topo.world_size)
    sim_state, col_state = state, wmesh.shard_stacked(state)
    for batch in round_batches(data, topo.world_size, h=2, batch=16, rounds=5):
        sim_state, sm = sim_step(sim_state, batch)
        col_state, cm = col_step(col_state, batch)
    assert float(sm["loss"]) == pytest.approx(float(cm["loss"]), rel=1e-4)
    for a, b in zip(
        jax.tree.leaves(sim_state.params), jax.tree.leaves(col_state.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5)


def test_slowmo_converges_and_momentum_engages():
    """SlowMo trains to low loss and its buffer is actually nonzero."""
    topo = RingTopology(8)
    model, cfg, init = _setup(topo, SlowMoConfig(beta=0.8), lr=5e-3)
    data = SyntheticClassification(n=2048)
    step = make_simulated_train_step(cfg, mlp_loss_fn(model))
    state = init_stacked_state(cfg, init, jax.random.key(1), topo.world_size)
    losses = []
    for batch in round_batches(data, topo.world_size, h=2, batch=32, rounds=40):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < 0.3 * losses[0]
    u_norm = sum(
        float(jnp.sum(jnp.abs(u))) for u in jax.tree.leaves(state.outer["u"])
    )
    assert u_norm > 0.0


def test_slowmo_update_math():
    """Pin the update equations on a scalar: d = x - y, u = beta*u + d,
    x' = x - alpha*u."""
    cfg = SlowMoConfig(beta=0.5, alpha=2.0)
    params = {"w": jnp.asarray(10.0)}
    state = slowmo_init(params)
    # base round moved params 10 -> 8: pseudo-gradient d = 2
    mixed = {"w": jnp.asarray(8.0)}
    new, state = slowmo_update(cfg, mixed, state)
    assert float(new["w"]) == pytest.approx(10.0 - 2.0 * 2.0)  # u = 2
    assert float(state["u"]["w"]) == pytest.approx(2.0)
    # next round from x=6, moved to 5: d = 1, u = 0.5*2 + 1 = 2, x' = 6 - 4
    new, state = slowmo_update(cfg, {"w": jnp.asarray(5.0)}, state)
    assert float(new["w"]) == pytest.approx(2.0)
    assert float(state["u"]["w"]) == pytest.approx(2.0)


def test_slowmo_preserves_bf16_param_dtype():
    cfg = SlowMoConfig()
    params = {"w": jnp.ones((4,), jnp.bfloat16)}
    state = slowmo_init(params)
    assert state["x"]["w"].dtype == jnp.float32  # f32 master copy
    new, _ = slowmo_update(cfg, params, state)
    assert new["w"].dtype == jnp.bfloat16
