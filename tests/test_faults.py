"""Fault tolerance: injected peer dropouts + non-finite failure detection.

The decentralized selling point (SURVEY.md §5): a dropped peer degrades a
round instead of deadlocking the job. Oracles: the masked mixing matrix's
algebraic properties, collective-vs-simulated agreement under the same
fault draws, convergence under sustained dropout, and NaN quarantine.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from consensusml_tpu.comm import WorkerMesh
from consensusml_tpu.consensus import FaultConfig, GossipConfig, masked_mixing_matrix
from consensusml_tpu.data import SyntheticClassification, round_batches
from consensusml_tpu.models import MLP, mlp_loss_fn
from consensusml_tpu.topology import DenseTopology, RingTopology, TorusTopology
from consensusml_tpu.train import (
    LocalSGDConfig,
    init_stacked_state,
    make_collective_train_step,
    make_simulated_train_step,
)


# ---------------------------------------------------------------------------
# masked mixing matrix algebra
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "topo", [RingTopology(8), TorusTopology(2, 4), DenseTopology(8)]
)
def test_masked_matrix_doubly_stochastic(topo):
    w = jnp.asarray(topo.mixing_matrix(), jnp.float32)
    rng = np.random.default_rng(0)
    for _ in range(5):
        alive = jnp.asarray(rng.integers(0, 2, size=8), jnp.float32)
        wp = np.asarray(masked_mixing_matrix(w, alive))
        np.testing.assert_allclose(wp.sum(0), 1.0, atol=1e-6)
        np.testing.assert_allclose(wp.sum(1), 1.0, atol=1e-6)
        assert (wp >= -1e-7).all()
        # dead workers keep their own value and give nothing to others
        for i in np.flatnonzero(np.asarray(alive) == 0):
            np.testing.assert_allclose(wp[i], np.eye(8)[i], atol=1e-7)
            assert np.allclose(np.delete(wp[:, i], i), 0.0)


def test_masked_matrix_all_alive_is_identity_op():
    topo = RingTopology(8)
    w = jnp.asarray(topo.mixing_matrix(), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(masked_mixing_matrix(w, jnp.ones(8))), np.asarray(w), atol=1e-7
    )


def test_fault_config_validation():
    with pytest.raises(ValueError, match="drop_prob"):
        FaultConfig(drop_prob=1.0)
    from consensusml_tpu.compress import TopKCompressor

    with pytest.raises(NotImplementedError, match="fault"):
        GossipConfig(
            topology=RingTopology(4),
            compressor=TopKCompressor(ratio=0.5),
            faults=FaultConfig(drop_prob=0.1),
        )


# ---------------------------------------------------------------------------
# backends agree and training survives dropouts
# ---------------------------------------------------------------------------


def _setup(topo, drop_prob, h=1):
    model = MLP(hidden=16)
    cfg = LocalSGDConfig(
        gossip=GossipConfig(
            topology=topo, faults=FaultConfig(drop_prob=drop_prob)
        ),
        optimizer=optax.sgd(0.05, momentum=0.9),
        h=h,
    )
    init = lambda rng: model.init(rng, jnp.zeros((1, 28, 28, 1)))["params"]
    return model, cfg, init


def test_collective_matches_simulated_under_dropout():
    topo = RingTopology(8)
    model, cfg, init = _setup(topo, drop_prob=0.5, h=2)
    data = SyntheticClassification(n=512)
    wmesh = WorkerMesh.create(topo, devices=jax.devices()[:8])
    step_c = make_collective_train_step(cfg, mlp_loss_fn(model), wmesh)
    step_s = make_simulated_train_step(cfg, mlp_loss_fn(model))
    state_c = init_stacked_state(cfg, init, jax.random.key(0), 8)
    state_c = wmesh.shard_stacked(state_c)
    state_s = init_stacked_state(cfg, init, jax.random.key(0), 8)

    alive_c, alive_s = [], []
    for batch in round_batches(data, 8, h=cfg.h, batch=16, rounds=4):
        state_c, m_c = step_c(state_c, wmesh.shard_stacked(batch))
        state_s, m_s = step_s(state_s, batch)
        alive_c.append(float(m_c["alive_frac"]))
        alive_s.append(float(m_s["alive_frac"]))

    # same rng streams -> identical fault draws on both backends
    assert alive_c == alive_s
    assert any(a < 1.0 for a in alive_c), "drop_prob=0.5 should drop someone in 4 rounds"
    for a, b in zip(jax.tree.leaves(state_c.params), jax.tree.leaves(state_s.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)


def test_training_converges_under_sustained_dropout():
    topo = DenseTopology(4)
    model, cfg, init = _setup(topo, drop_prob=0.3)
    data = SyntheticClassification(n=2048)
    step = make_simulated_train_step(cfg, mlp_loss_fn(model))
    state = init_stacked_state(cfg, init, jax.random.key(0), 4)

    losses = []
    for batch in round_batches(data, 4, h=cfg.h, batch=64, rounds=40):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < 0.5 * losses[0], f"no convergence under dropout: {losses[0]} -> {losses[-1]}"
    assert all(np.isfinite(losses))


# ---------------------------------------------------------------------------
# failure detection: NaN quarantine + rollback
# ---------------------------------------------------------------------------


def test_nan_worker_is_quarantined_and_recovers():
    """Worker 0 gets a poisoned (inf) batch for one round: its update must
    be rolled back, the NaN must never reach other workers, and the
    alive_frac metric must report the casualty."""
    topo = RingTopology(4)
    model, cfg, init = _setup(topo, drop_prob=0.0)
    data = SyntheticClassification(n=512)
    step = make_simulated_train_step(cfg, mlp_loss_fn(model))
    state = init_stacked_state(cfg, init, jax.random.key(0), 4)

    for r, batch in enumerate(round_batches(data, 4, h=1, batch=16, rounds=6)):
        if r == 2:  # poison worker 0's images for this round only
            img = np.array(batch["image"])  # writable copy
            img[0] = np.inf
            batch = dict(batch, image=jnp.asarray(img))
        state, m = step(state, batch)
        if r == 2:
            assert float(m["alive_frac"]) == pytest.approx(0.75)
        else:
            assert float(m["alive_frac"]) == 1.0
        assert np.isfinite(float(m["loss"]))

    for leaf in jax.tree.leaves(state.params):
        assert bool(jnp.all(jnp.isfinite(leaf))), "NaN leaked into params"
    # worker 0 re-synced through later gossip: disagreement stays bounded
    assert float(m["consensus_error"]) < 1.0
