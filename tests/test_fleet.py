"""Fleet tier: router placement, re-dispatch, supervision, canary (ISSUE 20).

Pinned properties:

- **Readiness gate** — ``/healthz`` answers 503 until the engine's
  warmup completes; a warming replica takes zero new streams.
- **Placement** — the router scores KV headroom per queued request over
  scraped signals; not-ready replicas take nothing; affinity keeps a
  (tenant, prefix)'s repeats on the replica whose prefix index is warm.
- **Re-dispatch** — a queue-full reject, dead connection, or cancelled
  terminal re-dispatches the stream as a CONTINUATION (prompt = ids +
  tokens already streamed, budget reduced) so an accepted stream is
  never lost.
- **Canary rollout** — the controller bumps ONE replica's artifact
  generation, soaks it against the alert plane, then promotes
  fleet-wide or rolls back by re-pinning the old meta FORWARD.

Fast tests run against stub line-JSON servers and fake replica handles
(no jax); the ``test_fleet_e2e_*`` tests spawn real in-process engines
and are slow-marked (tests/conftest.py).
"""

import json
import os
import socket
import threading
import time

import pytest

from consensusml_tpu.fleet import (
    CanaryState,
    ExternalReplica,
    FleetController,
    FleetRouter,
)
from consensusml_tpu.fleet.replicas import _http_json, scrape_signals
from consensusml_tpu.fleet.router import affinity_key, placement_score
from consensusml_tpu.serve.export import (
    META_NAME,
    bump_generation,
    pin_generation,
    serving_meta,
)

pytestmark = pytest.mark.serving


# ---------------------------------------------------------------------------
# Stub plumbing: line-JSON replica servers, fake handles, a fake fleet
# ---------------------------------------------------------------------------


class _StubServer:
    """Minimal line-JSON server standing in for one ServeServer replica:
    ``behavior(req, wfile)`` scripts what each accepted stream does
    (serve, die mid-stream, reject). Received requests are recorded."""

    def __init__(self, behavior):
        self.behavior = behavior
        self.requests: list[dict] = []
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(32)
        self._sock.settimeout(0.2)
        self.address = self._sock.getsockname()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            threading.Thread(
                target=self._serve, args=(conn,), daemon=True
            ).start()
        self._sock.close()

    def _serve(self, conn):
        try:
            with conn:
                f = conn.makefile("rwb")
                line = f.readline()
                if not line:
                    return
                req = json.loads(line)
                self.requests.append(req)
                self.behavior(req, f)
        except (BrokenPipeError, ConnectionResetError):
            pass

    def close(self):
        self._stop.set()
        self._thread.join(timeout=2.0)


def _serve_all(base):
    """Behavior: stream the full budget (tokens base+i), clean terminal."""

    def behavior(req, f):
        toks = [base + i for i in range(int(req["max_new_tokens"]))]
        for t in toks:
            f.write(json.dumps({"token": t}).encode() + b"\n")
            f.flush()
        f.write(
            json.dumps(
                {"done": True, "tokens": toks, "finish_reason": "max_tokens"}
            ).encode()
            + b"\n"
        )
        f.flush()

    return behavior


def _die_after(base, n):
    """Behavior: stream n tokens then drop the connection (no terminal)
    — what a killed replica's socket looks like from the router."""

    def behavior(req, f):
        for i in range(n):
            f.write(json.dumps({"token": base + i}).encode() + b"\n")
            f.flush()

    return behavior


def _cancel_after(base, n):
    """Behavior: stream n tokens then a ``finish_reason="cancelled"``
    terminal — the engine's non-drain shutdown sweep."""

    def behavior(req, f):
        for i in range(n):
            f.write(json.dumps({"token": base + i}).encode() + b"\n")
            f.flush()
        f.write(
            json.dumps(
                {"done": True, "tokens": [], "finish_reason": "cancelled"}
            ).encode()
            + b"\n"
        )
        f.flush()

    return behavior


def _reject(req, f):
    f.write(json.dumps({"error": "queue full: 0 free slots"}).encode() + b"\n")
    f.flush()


class _Fleet:
    def __init__(self, reps):
        self._reps = list(reps)

    def replicas(self):
        return list(self._reps)


class _FakeHandle:
    """A replica handle with scripted signals (router scoring tests)."""

    def __init__(self, name, address, *, ready=True, hbm=None, queue=0):
        self.name = name
        self.address = address
        self.artifact = None
        self.ready = ready
        self.hbm = hbm
        self.queue = queue

    def signals(self):
        return {
            "ready": self.ready,
            "alive": True,
            "hbm_free_bytes": self.hbm,
            "queue_depth": self.queue,
            "generation": None,
            "swap_rejected_total": None,
            "firing": [],
        }


class _FakeReplica:
    """A replica handle with lifecycle verbs recorded (controller tests);
    ``generation`` reads the artifact meta unless overridden — a fake
    that never "swaps" models the watcher that never lands."""

    def __init__(self, name, artifact=None, *, ready=True):
        self.name = name
        self.artifact = artifact
        self.address = ("127.0.0.1", 1)
        self.ready = ready
        self.firing: list[str] = []
        self.swap_rejected = None
        self.gen_override = "meta"
        self.drained = 0
        self.respawned = 0

    def is_ready(self):
        return self.ready

    def signals(self):
        gen = None
        if self.gen_override != "meta":
            gen = self.gen_override
        elif self.artifact:
            gen = int(serving_meta(self.artifact).get("generation", 0))
        return {
            "ready": self.ready,
            "alive": True,
            "hbm_free_bytes": None,
            "queue_depth": 0,
            "generation": gen,
            "swap_rejected_total": self.swap_rejected,
            "firing": list(self.firing),
        }

    def drain(self, timeout=None):
        self.drained += 1
        return True

    def respawn(self, block=True):
        self.respawned += 1


def _client(addr, ids, max_new, tenant=None):
    """One stream through the router: returns (streamed_tokens, terminal
    or error record)."""
    with socket.create_connection(addr, timeout=30) as s:
        f = s.makefile("rwb")
        req = {"ids": list(ids), "max_new_tokens": max_new}
        if tenant is not None:
            req["tenant"] = tenant
        f.write(json.dumps(req).encode() + b"\n")
        f.flush()
        toks = []
        for line in f:
            msg = json.loads(line)
            if "error" in msg or msg.get("done"):
                return toks, msg
            toks.append(msg["token"])
        return toks, None


def _report_quiesced(router, timeout=5.0):
    """The router bumps ``completed`` AFTER flushing the terminal to the
    client, so an immediate ``report()`` can race the last bump: poll
    until the accounting settles."""
    deadline = time.time() + timeout
    rep = router.report()
    while rep["lost_streams"] != 0 and time.time() < deadline:
        time.sleep(0.01)
        rep = router.report()
    return rep


def _stub_art(tmp_path, name, generation=1):
    d = tmp_path / name
    d.mkdir()
    (d / META_NAME).write_text(
        json.dumps({"config_name": "stub", "generation": generation})
    )
    return str(d)


# ---------------------------------------------------------------------------
# Satellite 1: /healthz readiness gates on warmup completion
# ---------------------------------------------------------------------------


class _StubEngine:
    def __init__(self):
        self.warmed = False

    def shutdown(self, drain=True, timeout=None):
        pass


def test_healthz_gates_on_engine_warmup():
    """A replica still paying warmup compiles answers 503 on /healthz
    (ready False) and flips to 200 the moment warmup completes — the
    signal the fleet router places zero streams on."""
    from consensusml_tpu.serve.server import ServeServer

    eng = _StubEngine()
    server = ServeServer(eng, metrics_port=0)
    try:
        host, port = server.metrics_address
        url = f"http://{host}:{port}/healthz"
        code, hz = _http_json(url)
        assert code == 503
        assert hz["ready"] is False and hz["ok"] is False
        # the scrape the router runs sees the same thing
        sig = scrape_signals((host, port))
        assert sig["ready"] is False and sig["alive"] is True

        eng.warmed = True
        code, hz = _http_json(url)
        assert code == 200
        assert hz["ready"] is True and hz["ok"] is True
        sig = scrape_signals((host, port))
        assert sig["ready"] is True
        # untouched gauges scrape as absent, never NaN (NaN would
        # poison placement_score's sort tuple)
        for k in ("hbm_free_bytes", "queue_depth", "generation",
                  "swap_rejected_total"):
            v = sig[k]
            assert v is None or v == v, f"{k} scraped as NaN"
    finally:
        server.shutdown(drain=False)


def test_scrape_signals_unreachable_means_not_ready():
    sig = scrape_signals(("127.0.0.1", 9))  # nothing listens on discard
    assert sig["ready"] is False and sig["alive"] is False
    assert scrape_signals(None)["ready"] is False


# ---------------------------------------------------------------------------
# Placement units: affinity key, score ordering
# ---------------------------------------------------------------------------


def test_affinity_key_tenant_and_prefix_sensitive():
    a = affinity_key("t0", [1, 2, 3, 4])
    assert a == affinity_key("t0", [1, 2, 3, 4])  # deterministic
    assert a != affinity_key("t1", [1, 2, 3, 4])  # tenant-sensitive
    assert a != affinity_key("t0", [1, 2, 3, 5])  # prefix-sensitive
    # only the first n_tokens ids participate: a long tail past the
    # prefix window does not split the key
    long0 = affinity_key("t0", list(range(16)) + [99])
    long1 = affinity_key("t0", list(range(16)) + [77])
    assert long0 == long1
    assert affinity_key(None, [1]) == affinity_key(None, [1])


def test_placement_score_orders_headroom_then_queue():
    hi = placement_score({"hbm_free_bytes": 100.0, "queue_depth": 0})
    lo = placement_score({"hbm_free_bytes": 10.0, "queue_depth": 0})
    assert hi > lo  # more headroom wins
    idle = placement_score({"hbm_free_bytes": 100.0, "queue_depth": 0})
    busy = placement_score({"hbm_free_bytes": 100.0, "queue_depth": 9})
    assert idle > busy  # headroom per queued request
    # no headroom gauge at all: least-queue tiebreak still orders
    q0 = placement_score({"hbm_free_bytes": None, "queue_depth": 0})
    q5 = placement_score({"hbm_free_bytes": None, "queue_depth": 5})
    assert q0 > q5
    # NaN gauges (a replica that never took a stream exposes NaN until
    # first set) read as "no signal" — the score stays finite and
    # totally ordered, so a fresh replica is never starved
    nan = float("nan")
    fresh = placement_score({"hbm_free_bytes": 100.0, "queue_depth": nan})
    assert fresh == placement_score(
        {"hbm_free_bytes": 100.0, "queue_depth": 0}
    )
    blank = placement_score({"hbm_free_bytes": nan, "queue_depth": nan})
    assert blank == placement_score(
        {"hbm_free_bytes": None, "queue_depth": 0}
    )
    assert fresh > blank > q5


def test_router_rejects_unknown_policy():
    with pytest.raises(ValueError):
        FleetRouter(_Fleet([]), policy="lowest_latency")


# ---------------------------------------------------------------------------
# Router: scoring, not-ready exclusion, affinity (fake handles + stubs)
# ---------------------------------------------------------------------------


def test_router_scores_headroom_and_skips_not_ready():
    """All placements land on the big-headroom replica; the not-ready
    handle (and the queue-crushed one) take zero new streams."""
    big = _StubServer(_serve_all(100))
    small = _StubServer(_serve_all(200))
    try:
        handles = [
            _FakeHandle("big", big.address, hbm=100e6, queue=0),
            _FakeHandle("small", small.address, hbm=1e6, queue=0),
            _FakeHandle("warming", ("127.0.0.1", 1), ready=False, hbm=1e9),
        ]
        router = FleetRouter(
            _Fleet(handles), policy="score", scrape_s=0.05, backoff_s=0.01
        )
        try:
            for i in range(5):  # distinct prompts: no affinity carryover
                toks, term = _client(router.address, [10 + i, 20 + i], 3)
                assert term["done"] and toks == [100, 101, 102]
                assert term["replica"] == "big"
            rep = _report_quiesced(router)
            assert rep["placements"] == {"big": 5}
            assert rep["lost_streams"] == 0
            assert len(small.requests) == 0
        finally:
            router.shutdown()
    finally:
        big.close()
        small.close()


def test_router_round_robin_rotates_over_ready_set():
    a = _StubServer(_serve_all(100))
    b = _StubServer(_serve_all(200))
    try:
        handles = [
            _FakeHandle("a", a.address, hbm=100e6),
            _FakeHandle("b", b.address, hbm=1e6),
        ]
        router = FleetRouter(_Fleet(handles), policy="round_robin")
        try:
            for i in range(6):
                _toks, term = _client(router.address, [i], 2)
                assert term["done"]
            rep = _report_quiesced(router)
            # rotation ignores headroom: the split is even
            assert rep["placements"] == {"a": 3, "b": 3}
            assert rep["policy"] == "round_robin"
        finally:
            router.shutdown()
    finally:
        a.close()
        b.close()


def test_router_affinity_repeats_and_breaks_on_deep_queue():
    """Repeats of one (tenant, prefix) ride the same replica (its prefix
    index is warm); once that replica's queue is past the affinity
    bound, placement falls back to score and moves off it."""
    a = _StubServer(_serve_all(100))
    b = _StubServer(_serve_all(200))
    try:
        ha = _FakeHandle("a", a.address, hbm=50e6)
        hb = _FakeHandle("b", b.address, hbm=50e6)
        router = FleetRouter(
            _Fleet([ha, hb]),
            policy="score",
            scrape_s=0.05,
            affinity_max_queue=4,
        )
        try:
            ids = [7, 8, 9]
            first = _client(router.address, ids, 2, tenant="acme")[1]
            pinned = first["replica"]
            for _ in range(3):
                term = _client(router.address, ids, 2, tenant="acme")[1]
                assert term["replica"] == pinned
            rep = _report_quiesced(router)
            assert rep["affinity_hits"] == 3
            assert rep["placements"][pinned] == 4

            # crush the pinned replica's queue past affinity_max_queue;
            # the next repeat must place elsewhere
            (ha if pinned == "a" else hb).queue = 50
            time.sleep(0.2)  # let the scrape loop publish the new depth
            term = _client(router.address, ids, 2, tenant="acme")[1]
            assert term["replica"] != pinned
        finally:
            router.shutdown()
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# Router: re-dispatch continuations (dead conn, cancelled, queue-full)
# ---------------------------------------------------------------------------


def test_router_redispatch_resumes_stream_after_replica_death():
    """Replica b dies after streaming 2 tokens; the stream resumes on a
    as a continuation (prompt = ids + the 2 streamed tokens, budget
    reduced) and the client sees one unbroken 6-token stream."""
    good = _StubServer(_serve_all(900))
    dying = _StubServer(_die_after(500, 2))
    try:
        reps = [  # equal scores: the name tiebreak picks "b" (max) first
            ExternalReplica(good.address, name="a"),
            ExternalReplica(dying.address, name="b"),
        ]
        router = FleetRouter(
            _Fleet(reps), scrape_s=0.05, backoff_s=0.01, max_retries=4
        )
        try:
            toks, term = _client(router.address, [1, 2, 3], 6)
            assert toks == [500, 501, 900, 901, 902, 903]
            assert term["done"] and term["tokens"] == toks
            assert term["redispatches"] == 1
            assert term["replica"] == "a"
            # the continuation carried the tokens already streamed and
            # the reduced budget
            assert dying.requests[0]["ids"] == [1, 2, 3]
            assert dying.requests[0]["max_new_tokens"] == 6
            assert good.requests[0]["ids"] == [1, 2, 3, 500, 501]
            assert good.requests[0]["max_new_tokens"] == 4
            rep = _report_quiesced(router)
            assert rep["lost_streams"] == 0
            assert rep["redispatches"] == 1
        finally:
            router.shutdown()
    finally:
        good.close()
        dying.close()


def test_router_redispatch_on_cancelled_terminal():
    """``finish_reason="cancelled"`` (the kill sweep's terminal) is a
    re-dispatch trigger, not a completion."""
    good = _StubServer(_serve_all(900))
    killed = _StubServer(_cancel_after(500, 2))
    try:
        reps = [
            ExternalReplica(good.address, name="a"),
            ExternalReplica(killed.address, name="b"),
        ]
        router = FleetRouter(
            _Fleet(reps), scrape_s=0.05, backoff_s=0.01, max_retries=4
        )
        try:
            toks, term = _client(router.address, [4, 5], 4)
            assert toks == [500, 501, 900, 901]
            assert term["done"] and term["replica"] == "a"
            assert term["redispatches"] == 1
            assert good.requests[0]["ids"] == [4, 5, 500, 501]
            assert _report_quiesced(router)["lost_streams"] == 0
        finally:
            router.shutdown()
    finally:
        good.close()
        killed.close()


def test_router_queue_full_reject_retries_next_best():
    good = _StubServer(_serve_all(900))
    full = _StubServer(_reject)
    try:
        reps = [
            ExternalReplica(good.address, name="a"),
            ExternalReplica(full.address, name="b"),
        ]
        router = FleetRouter(
            _Fleet(reps), scrape_s=0.05, backoff_s=0.01, max_retries=4
        )
        try:
            toks, term = _client(router.address, [1], 3)
            assert toks == [900, 901, 902]
            assert term["done"] and term["redispatches"] == 1
            rep = _report_quiesced(router)
            assert rep["completed"] == 1 and rep["lost_streams"] == 0
        finally:
            router.shutdown()
    finally:
        good.close()
        full.close()


def test_router_all_rejecting_yields_error_not_lost_stream():
    full0 = _StubServer(_reject)
    full1 = _StubServer(_reject)
    try:
        reps = [
            ExternalReplica(full0.address, name="a"),
            ExternalReplica(full1.address, name="b"),
        ]
        router = FleetRouter(
            _Fleet(reps), scrape_s=0.05, backoff_s=0.01, max_retries=3
        )
        try:
            toks, term = _client(router.address, [1], 3)
            assert toks == []
            assert "error" in term and "queue full" in term["error"]
            rep = _report_quiesced(router)
            assert rep["rejected"] == 1
            assert rep["lost_streams"] == 0
        finally:
            router.shutdown()
    finally:
        full0.close()
        full1.close()


# ---------------------------------------------------------------------------
# Controller: canary promote / rollback, sick drains, pin_generation
# ---------------------------------------------------------------------------


def test_pin_generation_is_a_forward_write(tmp_path):
    art = _stub_art(tmp_path, "art", generation=3)
    old = serving_meta(art)
    bump_generation(art)  # 4
    bump_generation(art)  # 5: the "bad" canary content
    pinned = pin_generation(art, old)
    assert pinned == 6
    meta = serving_meta(art)
    assert meta["generation"] == 6  # strictly above — watchers accept
    assert meta["rolled_back_from"] == 5
    assert meta["config_name"] == "stub"


def test_controller_canary_promotes_after_healthy_soak(tmp_path):
    arts = [_stub_art(tmp_path, f"art{i}") for i in range(3)]
    reps = [_FakeReplica(f"r{i}", arts[i]) for i in range(3)]
    c = FleetController(_Fleet(reps), soak_s=1.0, restart_sick=False)
    rec = c.start_canary(now=100.0)
    assert rec["replica"] == "r0" and rec["target_generation"] == 2
    # ONE replica's artifact advanced; the rest still serve the old gen
    assert serving_meta(arts[0])["generation"] == 2
    assert serving_meta(arts[1])["generation"] == 1
    doc = c.step(now=100.5)  # swapped, but the soak window is still open
    assert doc["canary"]["state"] == CanaryState.SOAKING
    doc = c.step(now=101.1)
    assert doc["canary"]["state"] == CanaryState.PROMOTED
    assert sorted(doc["canary"]["promoted"]) == ["r1", "r2"]
    assert serving_meta(arts[1])["generation"] == 2
    assert serving_meta(arts[2])["generation"] == 2
    kinds = [e["kind"] for e in c.events()]
    assert "canary-start" in kinds and "canary-promote" in kinds


def test_controller_canary_rolls_back_on_alert(tmp_path):
    arts = [_stub_art(tmp_path, f"art{i}") for i in range(2)]
    reps = [_FakeReplica(f"r{i}", arts[i]) for i in range(2)]
    c = FleetController(_Fleet(reps), soak_s=5.0, restart_sick=False)
    c.start_canary(now=0.0)
    reps[0].firing = ["spec-acceptance-collapse"]
    doc = c.step(now=0.2)
    assert doc["canary"]["state"] == CanaryState.ROLLED_BACK
    assert "spec-acceptance-collapse" in doc["canary"]["reason"]
    meta = serving_meta(arts[0])
    assert meta["generation"] == 3  # old meta re-pinned ABOVE the canary
    assert meta["rolled_back_from"] == 2
    assert serving_meta(arts[1])["generation"] == 1  # never touched
    # the rollout is resolved: a new canary may start
    reps[0].firing = []
    assert c.start_canary(now=10.0)["target_generation"] == 4


def test_controller_canary_rolls_back_on_swap_rejection_growth(tmp_path):
    art = _stub_art(tmp_path, "art")
    rep = _FakeReplica("r0", art)
    rep.swap_rejected = 0
    c = FleetController(_Fleet([rep]), soak_s=5.0, restart_sick=False)
    c.start_canary(now=0.0)
    rep.swap_rejected = 2  # the staged generation is being refused
    doc = c.step(now=0.2)
    assert doc["canary"]["state"] == CanaryState.ROLLED_BACK
    assert "swap-rejections(gauge)" in doc["canary"]["reason"]


def test_controller_canary_rolls_back_when_swap_never_lands(tmp_path):
    art = _stub_art(tmp_path, "art")
    rep = _FakeReplica("r0", art)
    rep.gen_override = 1  # the watcher never picks the bump up
    c = FleetController(
        _Fleet([rep]), soak_s=0.1, soak_timeout_s=5.0, restart_sick=False
    )
    c.start_canary(now=0.0)
    doc = c.step(now=1.0)
    assert doc["canary"]["state"] == CanaryState.SOAKING  # still waiting
    doc = c.step(now=6.0)
    assert doc["canary"]["state"] == CanaryState.ROLLED_BACK
    assert doc["canary"]["reason"] == ["swap-never-landed"]


def test_controller_canary_requires_ready_replica_and_single_soak(tmp_path):
    with pytest.raises(RuntimeError):
        FleetController(_Fleet([_FakeReplica("r0")])).start_canary()
    art = _stub_art(tmp_path, "art")
    c = FleetController(_Fleet([_FakeReplica("r0", art)]), soak_s=60.0)
    c.start_canary(now=0.0)
    with pytest.raises(RuntimeError):
        c.start_canary(now=1.0)  # one soak in flight at a time


def test_controller_drains_sick_replica_after_sustained_burn(tmp_path):
    reps = [_FakeReplica("r0"), _FakeReplica("r1")]
    c = FleetController(_Fleet(reps), sick_after_s=0.5)
    reps[1].firing = ["serve-queue-backlog"]
    c.step(now=0.0)  # registers the burn, inside the grace window
    assert reps[1].drained == 0
    c.step(now=1.0)  # sustained past sick_after_s: drain + respawn
    assert reps[1].drained == 1 and reps[1].respawned == 1
    assert reps[0].drained == 0
    kinds = [e["kind"] for e in c.events()]
    assert "drain" in kinds and "respawn" in kinds
    # a burn that CLEARS inside the window never drains
    reps[0].firing = ["serve-ttft-burn-rate"]
    c.step(now=2.0)
    reps[0].firing = []
    c.step(now=2.1)
    c.step(now=9.0)
    assert reps[0].drained == 0
    # attach-mode handles have no lifecycle verbs: sick is a no-op
    ext = ExternalReplica(("127.0.0.1", 1), name="att")
    sick = _FakeReplica("att")
    sick.drain = ext.drain  # RuntimeError, swallowed
    sick.firing = ["serve-queue-backlog"]
    c2 = FleetController(_Fleet([sick]), sick_after_s=0.0)
    c2.step(now=0.0)
    c2.step(now=1.0)  # must not raise


# ---------------------------------------------------------------------------
# Satellite 2: loadgen multi-target mode
# ---------------------------------------------------------------------------


def test_run_loadgen_emits_per_target_report():
    from tools.loadgen import run_loadgen

    calls = [0]
    lock = threading.Lock()

    def submit(ids, max_new, ctx=None, sampling=None):
        with lock:  # arrivals run on their own threads
            calls[0] += 1
            n = calls[0]
        return {
            "ttft_s": 0.01,
            "latency_s": 0.02,
            "tokens": [0] * max_new,
            "target": "t0" if n % 2 else "t1",
        }

    rep = run_loadgen(
        submit, n_requests=8, rate_rps=1000.0, prompt_lens=(2, 4),
        vocab=16, max_new_tokens=3,
    )
    assert sorted(rep["targets"]) == ["t0", "t1"]
    for block in rep["targets"].values():
        assert block["completed"] == 4
        assert block["tokens_out"] == 12
        assert block["ttft_p99_ms"] > 0

    def untagged(ids, max_new, ctx=None, sampling=None):
        return {"ttft_s": 0.01, "latency_s": 0.02, "tokens": [0]}

    rep = run_loadgen(
        untagged, n_requests=2, rate_rps=1000.0, prompt_lens=(2, 4),
        vocab=16, max_new_tokens=1,
    )
    assert rep["targets"] is None  # single-target path unchanged


def test_multi_socket_submit_round_robins_and_tags():
    from tools.loadgen import _multi_socket_submit

    a = _StubServer(_serve_all(100))
    b = _StubServer(_serve_all(200))
    try:
        submit = _multi_socket_submit([a.address, b.address])
        seen = [submit([1, 2], 2)["target"] for _ in range(4)]
        assert len(a.requests) == 2 and len(b.requests) == 2
        assert len(set(seen)) == 2
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# Slow e2e: real engines behind the router (names in conftest _SLOW_TESTS)
# ---------------------------------------------------------------------------


def _export_art(tmp_path, name="art0"):
    import jax

    from consensusml_tpu import configs
    from consensusml_tpu.serve.export import export_serving
    from consensusml_tpu.train import init_stacked_state

    bundle = configs.build("gpt2_topk", "smoke")
    state = init_stacked_state(
        bundle.cfg, bundle.init_params, jax.random.key(0), bundle.world_size
    )
    art = str(tmp_path / name)
    export_serving(art, state, config_name="gpt2_topk", round=0)
    return art


def _spawn_fleet(tmp_path, pool_blocks, lanes, *, prefix_cache=False):
    import shutil

    from consensusml_tpu.fleet import InProcessReplica, ReplicaSet
    from consensusml_tpu.serve import ServeConfig, load_engine

    art0 = _export_art(tmp_path)
    arts = [art0]
    for i in range(1, len(pool_blocks)):
        d = str(tmp_path / f"art{i}")
        shutil.copytree(art0, d)
        arts.append(d)

    def factory(i):
        def build():
            return load_engine(
                arts[i],
                ServeConfig(
                    num_slots=lanes[i], max_len=32, max_new_tokens=4,
                    kv_impl="paged", block_size=8,
                    num_blocks=pool_blocks[i], prefix_cache=prefix_cache,
                ),
            )

        return build

    reps = [
        InProcessReplica(factory(i), name=f"r{i}", artifact=arts[i])
        for i in range(len(pool_blocks))
    ]
    fleet = ReplicaSet(reps)
    fleet.spawn_all(block=True)
    return reps, fleet, arts


def test_fleet_e2e_placement_and_kill_redispatch(tmp_path):
    """The acceptance anchor: 3 real replicas on an imbalanced pool mix.
    Scored placement sends fewer streams to the tiny-pool replica than
    round-robin does, and a mid-run ``kill()`` of a big replica loses
    zero accepted streams — every client sees a complete stream, the
    supervisor respawns the corpse."""
    reps, fleet, _arts = _spawn_fleet(tmp_path, [8, 48, 48], [2, 8, 8])
    try:
        fleet.start_supervision()

        def run_n(router, n):
            for i in range(n):
                ids = [1 + (5 * i + j) % 32 for j in range(4)]
                _toks, term = _client(router.address, ids, 4)
                assert term.get("done"), term

        rr = FleetRouter(fleet, policy="round_robin", scrape_s=0.05)
        try:
            run_n(rr, 9)
            rr_r0 = rr.report()["placements"].get("r0", 0)
        finally:
            rr.shutdown()
        assert rr_r0 == 3  # rotation sends a third into the tiny pool

        scored = FleetRouter(
            fleet, policy="score", scrape_s=0.05, backoff_s=0.05
        )
        try:
            run_n(scored, 9)
            sc_rep = scored.report()
            assert sc_rep["placements"].get("r0", 0) < rr_r0
            assert sc_rep["lost_streams"] == 0

            # kill drill: concurrent streams, r1 dies once a few land
            n = 12
            results = [None] * n
            errs = []

            def one(i):
                try:
                    ids = [1 + (7 * i + j) % 32 for j in range(4)]
                    results[i] = _client(scored.address, ids, 4)
                except Exception as e:  # noqa: BLE001 — recorded, asserted
                    errs.append(repr(e))

            threads = [
                threading.Thread(target=one, args=(i,)) for i in range(n)
            ]
            for t in threads:
                t.start()
                time.sleep(0.01)
            deadline = time.time() + 120
            while (
                scored.report()["completed"] < 2 and time.time() < deadline
            ):
                time.sleep(0.01)
            reps[1].kill()
            for t in threads:
                t.join(timeout=180)
            assert not errs, errs
            for toks, term in results:
                assert term is not None and term.get("done"), (toks, term)
            rep = _report_quiesced(scored)
            assert rep["lost_streams"] == 0
            assert rep["completed"] == rep["accepted"]
            # the supervisor notices the corpse and respawns it
            deadline = time.time() + 300
            while not reps[1].is_ready() and time.time() < deadline:
                time.sleep(0.1)
            assert reps[1].is_ready()
            assert reps[1].restarts >= 1
        finally:
            scored.shutdown()
    finally:
        fleet.stop(drain=True)


def test_fleet_e2e_canary_promote_and_rollback(tmp_path):
    """Canary against live engines + generation watchers: a healthy soak
    promotes fleet-wide (every artifact and every engine reach the
    target generation); a second canary under an injected
    spec-acceptance-collapse alert rolls back by forward-pinning."""
    reps, fleet, arts = _spawn_fleet(tmp_path, [16, 16], [4, 4])
    ctl = FleetController(
        fleet, poll_s=0.05, soak_s=0.3, restart_sick=False
    )
    try:
        ctl.start()
        rec = ctl.start_canary()
        target = rec["target_generation"]

        def wait_state(want, timeout=60.0):
            deadline = time.time() + timeout
            while time.time() < deadline:
                st = ctl.canary_status()
                if st["state"] == want:
                    return st
                time.sleep(0.05)
            raise AssertionError(
                f"canary never reached {want}: {ctl.canary_status()}"
            )

        st = wait_state(CanaryState.PROMOTED)
        for art in arts:
            assert serving_meta(art)["generation"] >= target
        # the watchers landed the swap on every engine, zero drain
        deadline = time.time() + 60
        while time.time() < deadline and any(
            (r.signals()["generation"] or 0) < target for r in reps
        ):
            time.sleep(0.05)
        assert all(
            (r.signals()["generation"] or 0) >= target for r in reps
        )

        rec2 = ctl.start_canary()
        victim = next(r for r in reps if r.name == rec2["replica"])
        victim.inject_alert("spec-acceptance-collapse")
        st = wait_state(CanaryState.ROLLED_BACK)
        assert "spec-acceptance-collapse" in st["reason"]
        meta = serving_meta(rec2["artifact"])
        assert meta["rolled_back_from"] == rec2["target_generation"]
        assert meta["generation"] == rec2["target_generation"] + 1
        victim.clear_alerts()
    finally:
        ctl.stop()
        fleet.stop(drain=True)


def test_fleet_e2e_affinity_tracks_single_engine_prefix_rate(tmp_path):
    """Same-tenant repeats of one shared prefix all ride one replica, so
    the fleet's prefix hit-rate tracks what a single engine would see:
    every repeat after the first hits that replica's prefix index."""
    reps, fleet, _arts = _spawn_fleet(
        tmp_path, [32, 32], [4, 4], prefix_cache=True
    )
    router = FleetRouter(fleet, policy="score", scrape_s=0.05)
    try:
        prefix = [1 + (i % 32) for i in range(16)]  # two full blocks
        n = 6
        homes = set()
        for i in range(n):
            _toks, term = _client(
                router.address, prefix + [40 + i], 2, tenant="acme"
            )
            assert term.get("done"), term
            homes.add(term["replica"])
        assert len(homes) == 1  # affinity pinned the prefix to one home
        home = next(r for r in reps if r.name == next(iter(homes)))
        stats = home.engine.stats()["prefix_cache"]
        # every request after the first re-used the cached prefix blocks
        assert stats["hits"] >= n - 1
        assert router.report()["affinity_hits"] >= n - 1
    finally:
        router.shutdown()
        fleet.stop(drain=True)
