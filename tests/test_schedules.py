"""LR schedules / optimizer rebuilding (train.schedules + CLI flags)."""

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from consensusml_tpu.train import build_optimizer, lr_schedule


def _vals(s, steps):
    if callable(s):
        return [float(s(t)) for t in range(steps)]
    return [float(s)] * steps


def test_constant_without_warmup_is_a_float():
    assert lr_schedule("constant", 0.1, 100) == 0.1


def test_constant_with_warmup():
    s = lr_schedule("constant", 0.1, 100, warmup_steps=10)
    v = _vals(s, 100)
    assert v[0] == 0.0
    np.testing.assert_allclose(v[5], 0.05, atol=1e-6)
    assert all(abs(x - 0.1) < 1e-6 for x in v[10:])


def test_cosine_warmup_peak_decay():
    s = lr_schedule("cosine", 1.0, 100, warmup_steps=20)
    v = _vals(s, 101)
    assert v[0] == 0.0
    np.testing.assert_allclose(v[20], 1.0, atol=1e-6)
    assert v[60] < v[20] and v[99] < 0.01


def test_linear_decays_to_zero():
    s = lr_schedule("linear", 0.5, 100, warmup_steps=10)
    v = _vals(s, 101)
    np.testing.assert_allclose(v[10], 0.5, atol=1e-6)
    assert v[100] < 1e-6
    # monotone decay after warmup
    assert all(a >= b - 1e-9 for a, b in zip(v[10:-1], v[11:]))


def test_unknown_kind_raises():
    with pytest.raises(ValueError):
        lr_schedule("exponential", 0.1, 100)


def test_build_optimizer_clips_global_norm():
    tx = build_optimizer(
        optax.sgd, peak_lr=1.0, total_steps=10, grad_clip=1.0
    )
    params = {"w": jnp.zeros(4)}
    state = tx.init(params)
    grads = {"w": jnp.full(4, 100.0)}
    updates, _ = tx.update(grads, state, params)
    # global norm clipped to 1 then scaled by lr=1 (sgd negates)
    np.testing.assert_allclose(
        float(jnp.linalg.norm(updates["w"])), 1.0, rtol=1e-5
    )


def test_build_optimizer_schedule_reaches_optimizer():
    tx = build_optimizer(
        optax.sgd, peak_lr=1.0, kind="linear", total_steps=4, warmup_steps=0
    )
    params = {"w": jnp.ones(2)}
    state = tx.init(params)
    grads = {"w": jnp.ones(2)}
    norms = []
    for _ in range(4):
        updates, state = tx.update(grads, state, params)
        norms.append(float(jnp.abs(updates["w"][0])))
    assert norms[0] > norms[1] > norms[2] > norms[3]


def test_all_configs_expose_optimizer_factory():
    from consensusml_tpu import configs

    for name in configs.names():
        b = configs.build(name, "smoke")
        assert b.optimizer_factory is not None, name
        assert b.base_lr is not None, name
        tx = build_optimizer(
            b.optimizer_factory,
            peak_lr=b.base_lr,
            kind="cosine",
            total_steps=20,
            warmup_steps=4,
            grad_clip=1.0,
        )
        assert isinstance(tx, optax.GradientTransformation), name


def test_checkpoint_round_roundtrip(tmp_path):
    """save_state records the gossip round; checkpoint_round reads it
    back without restoring (the CLI uses it to extend LR schedules
    across --resume)."""
    import jax

    from consensusml_tpu.train.local_sgd import TrainState
    from consensusml_tpu.utils import (
        checkpoint_round,
        checkpoint_world_size,
        save_state,
    )

    state = TrainState(
        step=jnp.full((4,), 17, jnp.int32),
        params={"w": jnp.ones((4, 3))},
        model_state={},
        opt_state=(),
        rng=jax.random.split(jax.random.key(0), 4),
        gossip={},
    )
    path = save_state(str(tmp_path / "ck"), state, step=17)
    assert checkpoint_round(path) == 17
    assert checkpoint_world_size(path) == 4
    assert checkpoint_round(str(tmp_path / "missing")) is None


def test_lora_grad_clip_ignores_frozen_base():
    """--grad-clip on llama_lora must clip by the ADAPTER gradient norm:
    huge gradients on the frozen base weights may not scale the adapter
    update down."""
    import jax

    from consensusml_tpu import configs

    b = configs.build("llama_lora", "smoke")
    tx = build_optimizer(b.optimizer_factory, peak_lr=1.0, grad_clip=1.0)
    params = b.init_params(jax.random.key(0))
    state = tx.init(params)
    is_lora = lambda path: any("lora" in str(k).lower() for k in path)
    # tiny adapter grads (well under the clip), enormous base grads
    grads = jax.tree_util.tree_map_with_path(
        lambda path, p: jnp.full_like(p, 1e-3 if is_lora(path) else 1e6),
        params,
    )
    updates, _ = tx.update(grads, state, params)
    leaves = jax.tree_util.tree_leaves_with_path(updates)
    lora_norms = [
        float(jnp.max(jnp.abs(v))) for path, v in leaves if is_lora(path)
    ]
    frozen_norms = [
        float(jnp.max(jnp.abs(v))) for path, v in leaves if not is_lora(path)
    ]
    assert lora_norms and max(frozen_norms) == 0.0
    # un-over-clipped: adam with unclipped tiny grads moves ~lr; if the
    # frozen base norm (1e6-scale) drove the clip, this would be ~1e-9
    assert max(lora_norms) > 1e-3


def test_warmup_longer_than_schedule_raises():
    for kind in ("constant", "cosine", "linear"):
        with pytest.raises(ValueError, match="warmup"):
            lr_schedule(kind, 0.1, 10, warmup_steps=10)
