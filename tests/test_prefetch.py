"""Overlapped host→device feed tests (ISSUE 3): prefetch determinism,
no-host-sync-between-rounds, zero-copy slot staging, ring planning, and
the feed-stall telemetry contract (docs/observability.md)."""

import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from consensusml_tpu import native
from consensusml_tpu.data.prefetch import (
    DevicePrefetcher,
    FeedItem,
    prefetch_to_device,
)
from consensusml_tpu.data.native_pipeline import plan_ring

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

needs_native = pytest.mark.skipif(
    not native.available(), reason="native library not buildable here"
)


# ---------------------------------------------------------------------------
# DevicePrefetcher core (no native dependency)
# ---------------------------------------------------------------------------


def test_close_from_another_thread_unblocks_waiting_consumer():
    """A consumer blocked in __next__'s queue pop must wake to
    StopIteration when another thread (teardown, GC __del__) closes the
    prefetcher — even though close() drains the queue and the stopped
    producer never re-posts the end-of-stream sentinel."""

    def slow_source():
        yield {"x": np.zeros((2,), np.float32)}
        # block until closed: the consumer will be waiting on an empty
        # queue when close() arrives
        stop_evt.wait(timeout=20)

    stop_evt = threading.Event()
    pf = DevicePrefetcher(slow_source(), depth=1)
    assert next(pf) is not None  # drain the one staged batch

    result = {}

    def consume():
        try:
            next(pf)
            result["outcome"] = "item"
        except StopIteration:
            result["outcome"] = "stop"

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    import time as _time

    _time.sleep(0.2)  # let the consumer park in queue.get()
    pf.close()
    stop_evt.set()
    t.join(timeout=10)
    assert not t.is_alive(), "consumer never woke after close()"
    assert result["outcome"] == "stop"


def test_prefetcher_preserves_order_and_counts():
    src = [{"x": np.full((4,), i, np.float32)} for i in range(7)]
    pf = DevicePrefetcher(iter(src), depth=2)
    got = list(pf)
    assert len(got) == 7
    for i, b in enumerate(got):
        np.testing.assert_array_equal(np.asarray(b["x"]), src[i]["x"])
    assert pf.batches_out == 7
    assert pf.stall_seconds_total >= 0.0


def test_prefetcher_yields_device_arrays():
    import jax

    pf = DevicePrefetcher(iter([{"x": np.ones((2, 2), np.float32)}]), depth=1)
    (b,) = list(pf)
    assert isinstance(b["x"], jax.Array)


def test_prefetcher_depth_zero_is_passthrough():
    src = iter([1, 2, 3])
    assert prefetch_to_device(src, 0) is src


def test_prefetcher_on_done_fires_after_all_batches():
    done = []
    src = (
        FeedItem({"x": np.full((2,), i, np.float32)}, lambda i=i: done.append(i))
        for i in range(5)
    )
    got = list(DevicePrefetcher(src, depth=2))
    assert len(got) == 5
    # every completion hook fired (transfer done => host memory reusable)
    assert sorted(done) == [0, 1, 2, 3, 4]
    # releases are in acquisition order: the in-flight window is FIFO
    assert done == sorted(done)


def test_prefetcher_source_error_surfaces_to_consumer():
    def src():
        yield {"x": np.zeros((1,), np.float32)}
        raise RuntimeError("producer blew up")

    pf = DevicePrefetcher(src(), depth=2)
    it = iter(pf)
    next(it)
    with pytest.raises(RuntimeError, match="producer blew up"):
        next(it)


def test_prefetcher_feed_items_require_placement():
    src = (FeedItem({"x": np.zeros((1,), np.float32)}, lambda: None) for _ in range(2))
    pf = DevicePrefetcher(src, depth=1, place=False)
    with pytest.raises(RuntimeError, match="require.*place"):
        list(pf)


def test_prefetcher_close_is_idempotent_and_early():
    src = ({"x": np.full((2,), i, np.float32)} for i in range(100))
    pf = DevicePrefetcher(src, depth=2)
    next(iter(pf))
    pf.close()
    pf.close()
    assert not pf._thread.is_alive()
    # next() after close() raises instead of blocking on a dead queue
    with pytest.raises(StopIteration):
        next(iter(pf))


def test_prefetcher_stall_metrics_registered():
    from consensusml_tpu.obs import get_registry

    reg = get_registry()
    before = reg.counter("consensusml_feed_batches_total").value
    list(DevicePrefetcher(iter([{"x": np.zeros((1,), np.float32)}] * 3), depth=2))
    assert reg.counter("consensusml_feed_batches_total").value == before + 3
    # the gauge exists and carries the last round's wait
    assert reg.gauge("consensusml_feed_stall_seconds").value >= 0.0


def test_plan_ring_shapes_depth_and_threads():
    # depth always leaves slack beyond the prefetch window (no deadlock:
    # prefetch in-flight slots + 2 free for the producers)
    for prefetch in (1, 2, 4):
        depth, _ = plan_ring(8, 4, prefetch=prefetch)
        assert depth == prefetch + 2
    # nthreads scales with slot bytes within [2, cpus-2]
    _, small = plan_ring(8, 16 * 16 * 3, cpu_count=16)
    assert small == 2
    _, big = plan_ring(128, 224 * 224 * 3 * 4, cpu_count=16)
    assert big == 10  # ~77 MB slot => one thread per 8 MB
    _, capped = plan_ring(512, 224 * 224 * 3 * 4, cpu_count=8)
    assert capped == 6  # cpus-2 cap


# ---------------------------------------------------------------------------
# native zero-copy staging + end-to-end feed
# ---------------------------------------------------------------------------


def _mk_loader(**kw):
    proto = np.arange(10 * 16, dtype=np.float32).reshape(10, 16) / 100.0
    args = dict(
        kind="classification", samples_per_slot=8, sample_floats=16,
        sample_ints=1, nclasses_or_vocab=10, noise=0.1, prototypes=proto,
        depth=3, nthreads=2, seed=0,
    )
    args.update(kw)
    return native.NativeLoader(**args)


@needs_native
def test_acquire_view_matches_next_stream():
    """Zero-copy views carry the identical deterministic byte stream the
    copying consume path yields, and released slots recycle."""
    with _mk_loader(seed=21) as a, _mk_loader(seed=21) as b:
        for _ in range(7):  # > depth: slots must recycle through release
            idx, data, ints = a.acquire_view()
            assert not data.flags.writeable and not ints.flags.writeable
            ref_d, ref_i = b.next()
            np.testing.assert_array_equal(data, ref_d)
            np.testing.assert_array_equal(ints, ref_i)
            a.release_slot(idx)


@needs_native
def test_release_slot_after_close_is_noop():
    ld = _mk_loader()
    idx, _, _ = ld.acquire_view()
    ld.close()
    ld.release_slot(idx)  # must not crash


@needs_native
def test_native_cls_feed_deterministic_across_knobs():
    """Same seed ⇒ byte-identical batch sequence regardless of prefetch
    depth, ring threads, or overlap on/off (the ISSUE 3 determinism
    contract)."""
    from consensusml_tpu.data import SyntheticClassification, native_cls_feed

    ds = SyntheticClassification(n=64, image_shape=(6, 6, 1), classes=10)

    def collect(**kw):
        out = []
        for b in native_cls_feed(ds, 2, 2, 4, 5, seed=13, wire="u8", **kw):
            out.append(
                {k: np.array(v, copy=True) for k, v in b.items()}
            )
        return out

    base = collect(prefetch=0)  # overlap off
    assert base[0]["image"].shape == (2, 2, 4, 6, 6, 1)
    assert base[0]["image"].dtype == np.uint8
    for kw in (
        dict(prefetch=2),
        dict(prefetch=4, depth=8, nthreads=5),
        dict(prefetch=1, depth=3, nthreads=1),
    ):
        got = collect(**kw)
        assert len(got) == len(base)
        for x, y in zip(base, got):
            np.testing.assert_array_equal(x["image"], y["image"])
            np.testing.assert_array_equal(x["label"], y["label"])


@needs_native
def test_native_cls_feed_finalizes_loader_threads():
    """Exhausting (or closing) the feed tears the C++ producer ring
    down: the release closures are the last loader references, so after
    the prefetcher drains, refcounting destroys it — no thread leak, and
    crucially no destroy-before-drain (slots stay alive until every
    in-flight transfer completed)."""
    import gc
    import time

    from consensusml_tpu.data import SyntheticClassification, native_cls_feed

    ds = SyntheticClassification(n=32, image_shape=(4, 4, 1))
    gc.collect()
    before = threading.active_count()
    list(native_cls_feed(ds, 2, 1, 2, 4, seed=1, prefetch=2, nthreads=3))
    # consumed to exhaustion => prefetcher closed itself; loader refs
    # all dropped => producer threads joined by the destructor
    gc.collect()
    deadline = time.time() + 5
    while threading.active_count() > before and time.time() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= before

    # early abandonment via close(): same teardown
    pf = native_cls_feed(ds, 2, 1, 2, 50, seed=1, prefetch=2, nthreads=3)
    next(iter(pf))
    pf.close()
    gc.collect()
    deadline = time.time() + 5
    while threading.active_count() > before and time.time() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= before


@needs_native
def test_native_cls_feed_f32_wire_matches_plain_iterator():
    from consensusml_tpu.data import (
        SyntheticClassification,
        native_cls_feed,
        native_round_batches,
    )

    ds = SyntheticClassification(n=32, image_shape=(4, 4, 1))
    plain = list(native_round_batches(ds, 2, 1, 3, rounds=4, seed=5))
    feed = list(native_cls_feed(ds, 2, 1, 3, 4, seed=5, wire="f32"))
    for x, y in zip(plain, feed):
        np.testing.assert_array_equal(np.asarray(x["image"]), np.asarray(y["image"]))
        np.testing.assert_array_equal(np.asarray(x["label"]), np.asarray(y["label"]))


@needs_native
def test_overlapped_feed_issues_no_host_sync_between_rounds():
    """The consumer's critical path is a queue pop: no block_until_ready
    (or any host sync) from the consuming thread between rounds — waits
    happen on the prefetcher's background thread only."""
    import jax

    from consensusml_tpu.data import SyntheticClassification, native_cls_feed

    ds = SyntheticClassification(n=64, image_shape=(6, 6, 1), classes=10)
    calls = []
    real = jax.block_until_ready

    def spy(x):
        calls.append(threading.get_ident())
        return real(x)

    consumer = threading.get_ident()
    jax.block_until_ready = spy
    try:
        got = list(native_cls_feed(ds, 2, 1, 4, 6, seed=3, prefetch=2))
    finally:
        jax.block_until_ready = real
    assert len(got) == 6
    # the background thread syncs (slot-release bookkeeping); the
    # consumer thread must never
    assert consumer not in calls
    assert calls, "expected the producer thread to fence slot transfers"


@needs_native
def test_train_cli_auto_u8_wire_and_prefetch(tmp_path):
    """--native-loader defaults to the u8 wire on image configs and runs
    through the overlapped feed; --native-wire f32 still overrides."""
    env = {**os.environ, "JAX_PLATFORMS": ""}
    r = subprocess.run(
        [sys.executable, "train.py", "--config", "mnist_mlp", "--device",
         "cpu", "--backend", "simulated", "--rounds", "3",
         "--native-loader"],
        cwd=REPO, capture_output=True, text=True, timeout=600, env=env,
    )
    assert r.returncode == 0, r.stderr[-1500:]
    assert "native wire: u8 (auto" in r.stdout
    assert "rounds prefetched" in r.stdout
    r = subprocess.run(
        [sys.executable, "train.py", "--config", "mnist_mlp", "--device",
         "cpu", "--backend", "simulated", "--rounds", "2",
         "--native-loader", "--native-wire", "f32", "--prefetch-depth", "0"],
        cwd=REPO, capture_output=True, text=True, timeout=600, env=env,
    )
    assert r.returncode == 0, r.stderr[-1500:]
    assert "native wire: f32 (explicit)" in r.stdout
    assert "rounds prefetched" not in r.stdout  # overlap off


@needs_native
def test_perf_sweep_fed_input_smoke():
    """tools/perf_sweep.py --fed-input emits a parseable JSON table on
    the CPU backend (the CI smoke of the depth x nthreads x wire sweep)."""
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "BENCH_DEVICE": "cpu",
        "SWEEP_FED_BATCH": "2",
        "SWEEP_FED_IMAGE": "16",
        "SWEEP_FED_STEPS": "2",
        "SWEEP_FED_MODEL": "tiny",
    }
    r = subprocess.run(
        [sys.executable, os.path.join("tools", "perf_sweep.py"),
         "--fed-input", "3:1:u8:2"],
        cwd=REPO, capture_output=True, text=True, timeout=600, env=env,
    )
    assert r.returncode == 0, r.stderr[-1500:]
    tables = [
        l for l in r.stdout.splitlines() if l.startswith("FED_TABLE ")
    ]
    assert tables, r.stdout[-1500:]
    table = json.loads(tables[-1][len("FED_TABLE "):])
    assert len(table) == 1
    row = table[0]
    assert row["wire"] == "u8" and row["prefetch"] == 2
    assert row["imgs_sec"] > 0
    assert 0.0 <= row["prefetch_overlap_pct"] <= 100.0
