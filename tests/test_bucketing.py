"""Bucketed gossip wire (consensus/bucketing.py + GossipConfig.bucket_bytes).

Covers: plan pack/unpack exactness (odd sizes, mixed dtypes, cap edge
cases), bucketed-vs-per-leaf round equivalence for dense/masked/CHOCO on
both backends, wire accounting (never larger than per-leaf), the lifted
overlap+compression restriction, and the dispatch-count reduction the
bucketing exists for (jaxpr op counts on the GPT-2-medium tree — CI has
no TPU, so op counts stand in for launch latency).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from consensusml_tpu.comm import WorkerMesh, simulated
from consensusml_tpu.compress import (
    ChunkedTopKCompressor,
    IdentityCompressor,
    TopKCompressor,
    topk_int8_compressor,
)
from consensusml_tpu.consensus import (
    ConsensusEngine,
    FaultConfig,
    GossipConfig,
    OverlapState,
    build_plan,
)
from consensusml_tpu.topology import DenseTopology, RingTopology

from tests.conftest import compat_shard_map

_shard_map = compat_shard_map()

WORLD = 8
TOPO = RingTopology(WORLD)

# chunk-decomposable codec => bucketed by default; impl="jnp" so the CPU
# mesh runs the exact math the kernels implement
CHUNKED = ChunkedTopKCompressor(chunk=128, k_per_chunk=8, impl="jnp")


def _tree(seed=0, world=WORLD):
    """Odd-sized leaves, one below the codec chunk — the shapes where
    per-leaf/bucketed divergence would show."""
    rng = np.random.default_rng(seed)
    return {
        "w": jnp.asarray(rng.normal(size=(world, 40, 13)), jnp.float32),
        "b": jnp.asarray(rng.normal(size=(world, 7)), jnp.float32),
        "v": jnp.asarray(rng.normal(size=(world, 300)), jnp.float32),
    }


def _pair(**kw):
    """(bucketed engine, per-leaf engine) for the same gossip config."""
    bucketed = ConsensusEngine(GossipConfig(topology=TOPO, **kw))
    per_leaf = ConsensusEngine(
        GossipConfig(topology=TOPO, bucket_bytes=None, **kw)
    )
    assert bucketed.bucketed and not per_leaf.bucketed
    return bucketed, per_leaf


def _run_sim(engine, tree, rounds, alive=None):
    w = simulated.mixing_matrix(engine.topology)
    state = engine.init_state(tree, world_size=WORLD)
    for _ in range(rounds):
        tree, state = engine.round_simulated(tree, state, w, alive=alive)
    return tree


def _run_col(engine, stacked, rounds):
    wmesh = WorkerMesh.create(engine.topology, platform="cpu")
    axes = engine.topology.axis_names

    @jax.jit
    @functools.partial(
        _shard_map, mesh=wmesh.mesh, in_specs=P(*axes), out_specs=P(*axes)
    )
    def run(tree):
        state = engine.init_state(tree)
        for _ in range(rounds):
            tree, state = engine.round_collective(tree, state)
        return tree

    return run(stacked)


# ---------------------------------------------------------------------------
# plan mechanics
# ---------------------------------------------------------------------------


def test_plan_roundtrip_odd_sizes_mixed_dtypes():
    """(c) pack(unpack) is exact for odd-sized, mixed-dtype trees, and
    buckets stay dtype-homogeneous."""
    rng = np.random.default_rng(3)
    leaves = [
        jnp.asarray(rng.normal(size=(17, 3)), jnp.float32),
        jnp.asarray(rng.normal(size=(5,)), jnp.bfloat16),
        jnp.asarray(rng.integers(0, 100, size=(9, 2)), jnp.int32),
        jnp.asarray(rng.normal(size=(1,)), jnp.float32),
        jnp.asarray(rng.normal(size=(250,)), jnp.bfloat16),
    ]
    plan = build_plan(
        [(x.shape, x.dtype) for x in leaves], bucket_bytes=1 << 20, align=128
    )
    for b in plan.buckets:
        for bl in b.leaves:
            assert leaves[bl.index].dtype == b.dtype
            assert bl.padded % 128 == 0
    bufs = plan.pack(leaves)
    back = plan.unpack(bufs)
    for orig, got in zip(leaves, back):
        assert orig.dtype == got.dtype and orig.shape == got.shape
        np.testing.assert_array_equal(np.asarray(orig), np.asarray(got))
    # stacked form round-trips too
    stacked = [jnp.stack([x, x]) for x in leaves]
    back = plan.unpack(plan.pack(stacked, stacked=True), stacked=True)
    for orig, got in zip(stacked, back):
        np.testing.assert_array_equal(np.asarray(orig), np.asarray(got))


def test_plan_cap_edge_cases():
    """(d) one giant bucket vs one leaf per bucket; an over-cap leaf gets
    its own bucket (leaves never split)."""
    shapes = [((64,), jnp.float32), ((64,), jnp.float32), ((4096,), jnp.float32)]
    giant = build_plan(shapes, bucket_bytes=1 << 30)
    assert giant.num_buckets == 1
    tiny = build_plan(shapes, bucket_bytes=1)  # every leaf overflows the cap
    assert tiny.num_buckets == len(shapes)
    # the 16 KiB leaf exceeds a 1 KiB cap but still lands (alone)
    mixed = build_plan(shapes, bucket_bytes=1024)
    assert mixed.num_buckets == 2
    assert {tuple(bl.index for bl in b.leaves) for b in mixed.buckets} == {
        (0, 1), (2,),
    }


def test_engine_path_selection():
    """Bucketing engages for exact mixing and chunk-decomposable codecs;
    global top-k, push-sum, fused_codec, and bucket_bytes=None fall back."""
    mk = lambda **kw: ConsensusEngine(GossipConfig(topology=TOPO, **kw))
    assert mk().bucketed
    assert mk(compressor=CHUNKED, gamma=0.5).bucketed
    assert not mk(compressor=TopKCompressor(ratio=0.25), gamma=0.5).bucketed
    assert not mk(bucket_bytes=None).bucketed
    assert not mk(push_sum=True).bucketed
    assert not mk(
        compressor=CHUNKED, gamma=0.5, fused_codec=True
    ).bucketed
    with pytest.raises(ValueError, match="bucket_bytes"):
        GossipConfig(topology=TOPO, bucket_bytes=0)


# ---------------------------------------------------------------------------
# (a) bucketed round == per-leaf round, all variants, both backends
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "kw",
    [
        {},  # dense
        dict(compressor=CHUNKED, gamma=0.5),  # CHOCO, chunk-decomposable
        dict(compressor=IdentityCompressor(), gamma=1.0),
    ],
    ids=["dense", "choco", "identity"],
)
def test_bucketed_matches_per_leaf_simulated(kw):
    eb, ep = _pair(**kw)
    got = _run_sim(eb, _tree(), rounds=4)
    want = _run_sim(ep, _tree(), rounds=4)
    for k in got:
        np.testing.assert_allclose(
            np.asarray(got[k]), np.asarray(want[k]), rtol=1e-6, atol=1e-7
        )


def test_bucketed_masked_matches_per_leaf_simulated():
    """Masked (fault-model) exact mixing: same alive draw, same result."""
    eb, ep = _pair(faults=FaultConfig(drop_prob=0.5))
    alive = jnp.asarray([1, 0, 1, 1, 0, 1, 1, 1], jnp.float32)
    got = _run_sim(eb, _tree(1), rounds=3, alive=alive)
    want = _run_sim(ep, _tree(1), rounds=3, alive=alive)
    for k in got:
        np.testing.assert_allclose(
            np.asarray(got[k]), np.asarray(want[k]), rtol=1e-6, atol=1e-7
        )


@pytest.mark.parametrize(
    "kw",
    [{}, dict(compressor=CHUNKED, gamma=0.5)],
    ids=["dense", "choco"],
)
def test_bucketed_matches_per_leaf_collective(kw):
    eb, ep = _pair(**kw)
    got = _run_col(eb, _tree(2), rounds=3)
    want = _run_col(ep, _tree(2), rounds=3)
    for k in got:
        np.testing.assert_allclose(
            np.asarray(got[k]), np.asarray(want[k]), rtol=1e-6, atol=1e-7
        )


def test_bucketed_collective_matches_simulated():
    """Cross-backend parity stays intact on the bucketed wire (the two
    backends must build the identical plan from per-worker shapes)."""
    for kw in ({}, dict(compressor=CHUNKED, gamma=0.5)):
        eng = ConsensusEngine(GossipConfig(topology=TOPO, **kw))
        assert eng.bucketed
        got_c = _run_col(eng, _tree(4), rounds=3)
        got_s = _run_sim(eng, _tree(4), rounds=3)
        for k in got_c:
            np.testing.assert_allclose(
                np.asarray(got_c[k]), np.asarray(got_s[k]),
                rtol=1e-5, atol=1e-6,
            )


def test_bucketed_composed_codec_close_to_per_leaf():
    """The config-5 composed codec (chunked top-k + int8-quantized
    values): bucketing coalesces the VALUE vectors before the outer int8
    pass, so outputs agree to quantization noise, not bit-exactly — and
    both stay contractive."""
    comp = topk_int8_compressor(chunk=128, k=32, impl="jnp")
    eb, ep = _pair(compressor=comp, gamma=0.4)
    got = _run_sim(eb, _tree(5), rounds=6)
    want = _run_sim(ep, _tree(5), rounds=6)
    err = lambda t: float(
        ConsensusEngine(GossipConfig(topology=TOPO)).consensus_error_simulated(t)
    )
    e0 = err(_tree(5))
    assert err(got) < 0.7 * e0 and err(want) < 0.7 * e0
    for k in got:
        np.testing.assert_allclose(
            np.asarray(got[k]), np.asarray(want[k]), rtol=0.02, atol=0.02
        )


def test_bucketed_dense_topology_psum_path():
    """uses_psum topologies mix per bucket through pmean — exact consensus
    in one round, bit-matching the per-leaf result."""
    topo = DenseTopology(4)
    eng_b = ConsensusEngine(GossipConfig(topology=topo))
    eng_p = ConsensusEngine(GossipConfig(topology=topo, bucket_bytes=None))
    tree = _tree(6, world=4)
    w = simulated.mixing_matrix(topo)
    got, _ = eng_b.round_simulated(dict(tree), None, w)
    want, _ = eng_p.round_simulated(dict(tree), None, w)
    for k in tree:
        np.testing.assert_allclose(
            np.asarray(got[k]), np.asarray(want[k]), rtol=1e-6, atol=1e-7
        )


# ---------------------------------------------------------------------------
# (b) wire accounting
# ---------------------------------------------------------------------------


def test_wire_bytes_unchanged_or_smaller():
    tree = {
        "w": jnp.zeros((40, 13), jnp.float32),
        "b": jnp.zeros((7,), jnp.float32),
        "v": jnp.zeros((300,), jnp.float32),
    }
    # dense: bucketing is pure coalescing — identical byte count
    eb, ep = _pair()
    assert eb.wire_bytes_per_round(tree) == ep.wire_bytes_per_round(tree)
    # chunked top-k: leaf-aligned packing mirrors the codec's own per-leaf
    # padding — identical
    eb, ep = _pair(compressor=CHUNKED, gamma=0.5)
    assert eb.wire_bytes_per_round(tree) == ep.wire_bytes_per_round(tree)
    # composed codec at the config-5 shape (k=8 winners per chunk): the
    # coalesced value vector amortizes the outer int8 codec's per-leaf
    # scale/index overhead — not larger (the accounting is exact either
    # way: wire_bytes_per_round reports the padded bucket payload)
    comp = topk_int8_compressor(chunk=128, k=8, impl="jnp")
    eb, ep = _pair(compressor=comp, gamma=0.5)
    assert eb.wire_bytes_per_round(tree) <= ep.wire_bytes_per_round(tree)


# ---------------------------------------------------------------------------
# overlap + compression (lifted on the bucketed path only)
# ---------------------------------------------------------------------------


def test_overlap_compression_gate():
    """Per-leaf/fused/non-decomposable stay rejected; the bucketed path
    with a chunk-decomposable deterministic codec is allowed."""
    ok = GossipConfig(
        topology=TOPO, overlap=True, compressor=CHUNKED, gamma=0.4
    )
    assert ConsensusEngine(ok).bucketed
    with pytest.raises(NotImplementedError, match="compression"):
        GossipConfig(
            topology=TOPO, overlap=True,
            compressor=TopKCompressor(ratio=0.1),  # not chunk-decomposable
        )
    with pytest.raises(NotImplementedError, match="compression"):
        GossipConfig(
            topology=TOPO, overlap=True, compressor=CHUNKED,
            bucket_bytes=None,
        )
    with pytest.raises(NotImplementedError, match="warmup|refresh|compose"):
        GossipConfig(
            topology=TOPO, overlap=True, compressor=CHUNKED,
            codec_warmup_rounds=2,
        )
    from consensusml_tpu.compress import QSGDCompressor

    with pytest.raises(NotImplementedError, match="STOCHASTIC"):
        GossipConfig(
            topology=TOPO, overlap=True, compressor=QSGDCompressor(chunk=128)
        )


def test_overlap_identity_codec_equals_exact_overlap():
    """Q=identity, gamma=1: the delayed CHOCO correction IS the delayed
    (W - I) z — anchors the compressed-overlap algebra to the tested
    exact mode."""
    e_id = ConsensusEngine(
        GossipConfig(
            topology=TOPO, overlap=True,
            compressor=IdentityCompressor(), gamma=1.0,
        )
    )
    e_ex = ConsensusEngine(GossipConfig(topology=TOPO, overlap=True))
    w = simulated.mixing_matrix(TOPO)
    zi, ze = _tree(7), _tree(7)
    si = e_id.init_state(zi, world_size=WORLD)
    se = e_ex.init_state(ze, world_size=WORLD)
    for _ in range(5):
        zi = e_id.apply_correction(zi, si)
        si = e_id.correction_simulated(zi, w, si)
        ze = e_ex.apply_correction(ze, se)
        se = e_ex.correction_simulated(ze, w)
        for k in zi:
            np.testing.assert_allclose(
                np.asarray(zi[k]), np.asarray(ze[k]), rtol=1e-5, atol=1e-6
            )


def test_overlap_choco_contracts_and_preserves_mean():
    # k=16/128: CHOCO's stable gamma shrinks with the compression ratio
    # (docs/convergence.md), and the delayed correction inherits that —
    # the 1/16 codec at gamma 0.4 sits outside the contraction region
    comp = ChunkedTopKCompressor(chunk=128, k_per_chunk=16, impl="jnp")
    eng = ConsensusEngine(
        GossipConfig(topology=TOPO, overlap=True, compressor=comp, gamma=0.4)
    )
    w = simulated.mixing_matrix(TOPO)
    z = _tree(8)
    mean0 = {k: np.asarray(v).mean(0) for k, v in z.items()}
    err0 = float(eng.consensus_error_simulated(z))
    st = eng.init_state(z, world_size=WORLD)
    assert isinstance(st, OverlapState) and st.choco is not None
    for _ in range(60):
        z = eng.apply_correction(z, st)
        st = eng.correction_simulated(z, w, st)
    assert float(eng.consensus_error_simulated(z)) < 0.15 * err0
    for k in z:  # delayed corrections still cancel across workers
        np.testing.assert_allclose(np.asarray(z[k]).mean(0), mean0[k], atol=1e-4)


def test_overlap_compressed_collective_matches_simulated():
    eng = ConsensusEngine(
        GossipConfig(topology=TOPO, overlap=True, compressor=CHUNKED, gamma=0.4)
    )
    wmesh = WorkerMesh.create(TOPO, platform="cpu")

    @jax.jit
    @functools.partial(
        _shard_map,
        mesh=wmesh.mesh,
        in_specs=P(*TOPO.axis_names),
        out_specs=P(*TOPO.axis_names),
    )
    def run(tree):
        st = eng.init_state(tree)
        for _ in range(4):
            tree = eng.apply_correction(tree, st)
            st = eng.correction_collective(tree, st)
        return tree

    got_c = run(_tree(9))
    w = simulated.mixing_matrix(TOPO)
    z = _tree(9)
    st = eng.init_state(z, world_size=WORLD)
    for _ in range(4):
        z = eng.apply_correction(z, st)
        st = eng.correction_simulated(z, w, st)
    for k in z:
        np.testing.assert_allclose(
            np.asarray(got_c[k]), np.asarray(z[k]), rtol=1e-5, atol=1e-6
        )


def test_overlap_compressed_bn_stats_ride_exact_correction():
    """The "auto" compress filter holds in overlap mode too: model_state
    gets the plain (W - I) z correction, params the CHOCO one."""
    eng = ConsensusEngine(
        GossipConfig(topology=TOPO, overlap=True, compressor=CHUNKED, gamma=0.4)
    )
    rng = np.random.default_rng(11)
    tree = {
        "params": {"w": jnp.asarray(rng.normal(size=(WORLD, 40, 13)), jnp.float32)},
        "model_state": {
            "var": jnp.asarray(1.0 + rng.random((WORLD, 33)), jnp.float32)
        },
    }
    w = simulated.mixing_matrix(TOPO)
    st = eng.init_state(tree, world_size=WORLD)
    # CHOCO tracking covers params only
    assert len(jax.tree.leaves(st.choco.xhat)) == 1
    st2 = eng.correction_simulated(tree, w, st)
    want = simulated.mix_stacked(tree["model_state"]["var"], w) - tree[
        "model_state"
    ]["var"]
    np.testing.assert_allclose(
        np.asarray(st2.correction["model_state"]["var"]),
        np.asarray(want), rtol=1e-6, atol=1e-7,
    )


# ---------------------------------------------------------------------------
# dispatch counts (the point of the whole exercise)
# ---------------------------------------------------------------------------


def _count_primitives(jaxpr, counts):
    for eqn in jaxpr.eqns:
        counts[eqn.primitive.name] = counts.get(eqn.primitive.name, 0) + 1
        for v in eqn.params.values():
            for sub in v if isinstance(v, (list, tuple)) else [v]:
                if hasattr(sub, "eqns"):
                    _count_primitives(sub, counts)
                elif hasattr(sub, "jaxpr"):
                    _count_primitives(sub.jaxpr, counts)
    return counts


@pytest.mark.slow  # the PER-LEAF trace over 292 leaves takes ~25 s
def test_gpt2_medium_dispatch_reduction():
    """On the GPT-2-medium tree (292 leaves), the bucketed round must
    issue <= 1/10th the per-leaf path's ppermute AND compress dispatches.
    Asserted on jaxpr op counts (CI has no TPU to measure launches on);
    shapes only — nothing is materialized."""
    from consensusml_tpu.models.gpt2 import GPT2Config, GPT2LM

    model = GPT2LM(config=GPT2Config())  # gpt2-medium dims
    shapes = jax.eval_shape(
        lambda: model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))[
            "params"
        ]
    )
    assert len(jax.tree.leaves(shapes)) == 292
    stacked = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct((WORLD,) + x.shape, x.dtype), shapes
    )
    wmesh = WorkerMesh.create(TOPO, platform="cpu")
    comp = topk_int8_compressor(chunk=512, k=8, impl="auto")  # config 5

    def counts_for(bucket_bytes):
        eng = ConsensusEngine(
            GossipConfig(
                topology=TOPO, compressor=comp, gamma=0.1,
                bucket_bytes=bucket_bytes,
            )
        )

        def round_fn(tree):
            st = eng.init_state(tree)
            out, _ = eng.round_collective(tree, st)
            return out

        f = functools.partial(
            _shard_map,
            mesh=wmesh.mesh,
            in_specs=P(*TOPO.axis_names),
            out_specs=P(*TOPO.axis_names),
        )(round_fn)
        return _count_primitives(jax.make_jaxpr(f)(stacked).jaxpr, {})

    bucketed = counts_for(4 * 2**20)
    per_leaf = counts_for(None)
    # compress dispatches: one top_k per compress call on this codec
    assert per_leaf["top_k"] == 292
    assert per_leaf["ppermute"] >= 292 * 2  # >= one send per leaf per shift
    assert bucketed["ppermute"] * 10 <= per_leaf["ppermute"]
    assert bucketed["top_k"] * 10 <= per_leaf["top_k"]
