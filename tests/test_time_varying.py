"""Time-varying gossip: one-peer exponential topology through both
backends.

The collective backend dispatches the round's phase with ``lax.switch``
(static ppermute perms per branch), the simulated backend indexes stacked
per-phase mixing matrices — these tests pin (a) backend agreement, (b) the
finite-time exact-averaging property on 2^tau workers, and (c) interplay
with faults and CHOCO compression.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from consensusml_tpu.comm import WorkerMesh
from consensusml_tpu.compress import Int8Compressor
from consensusml_tpu.consensus import FaultConfig, GossipConfig
from consensusml_tpu.data import SyntheticClassification, round_batches
from consensusml_tpu.models import MLP, mlp_loss_fn
from consensusml_tpu.topology import OnePeerExponentialTopology, RingTopology
from consensusml_tpu.train import (
    LocalSGDConfig,
    init_stacked_state,
    make_collective_train_step,
    make_simulated_train_step,
)


def _setup(topo, h=1, lr=1e-2, compressor=None, gamma=1.0, faults=None):
    model = MLP(hidden=16)
    cfg = LocalSGDConfig(
        gossip=GossipConfig(
            topology=topo, compressor=compressor, gamma=gamma, faults=faults
        ),
        optimizer=optax.adam(lr),
        h=h,
    )
    init = lambda rng: model.init(rng, jnp.zeros((1, 28, 28, 1)))["params"]
    return model, cfg, init


def test_collective_matches_simulated_onepeer():
    """Phase dispatch via lax.switch == stacked-matrix indexing, over more
    rounds than the period so every phase is exercised."""
    topo = OnePeerExponentialTopology(8)
    model, cfg, init = _setup(topo, h=2)
    data = SyntheticClassification(n=1024)
    loss_fn = mlp_loss_fn(model)

    sim_step = make_simulated_train_step(cfg, loss_fn)
    wmesh = WorkerMesh.create(topo, platform="cpu")
    col_step = make_collective_train_step(cfg, loss_fn, wmesh)

    state = init_stacked_state(cfg, init, jax.random.key(5), topo.world_size)
    sim_state, col_state = state, wmesh.shard_stacked(state)
    for batch in round_batches(data, topo.world_size, h=2, batch=16, rounds=5):
        sim_state, sm = sim_step(sim_state, batch)
        col_state, cm = col_step(col_state, batch)
    assert float(sm["loss"]) == pytest.approx(float(cm["loss"]), rel=1e-4)
    assert float(sm["consensus_error"]) == pytest.approx(
        float(cm["consensus_error"]), rel=1e-3, abs=1e-5
    )
    for a, b in zip(
        jax.tree.leaves(sim_state.params), jax.tree.leaves(col_state.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5)


def test_onepeer_reaches_exact_consensus_in_one_period():
    """With lr=0 (pure gossip) 8 workers agree EXACTLY after 3 rounds —
    the one-peer exponential finite-time guarantee, running on the real
    collective path."""
    topo = OnePeerExponentialTopology(8)
    model, cfg, init = _setup(topo, h=1, lr=0.0)
    data = SyntheticClassification(n=256)
    wmesh = WorkerMesh.create(topo, platform="cpu")
    step = make_collective_train_step(cfg, mlp_loss_fn(model), wmesh)
    state = wmesh.shard_stacked(
        init_stacked_state(cfg, init, jax.random.key(0), topo.world_size)
    )
    errs = []
    for batch in round_batches(data, topo.world_size, h=1, batch=8, rounds=4):
        state, m = step(state, batch)
        errs.append(float(m["consensus_error"]))
    assert errs[0] > 1e-2  # random inits disagree
    assert errs[2] < 1e-5, f"period=3 must reach consensus, errs={errs}"


def test_onepeer_beats_ring_consensus_decay():
    """Same training run, one ppermute per round each: one-peer exp must
    drive consensus error well below the ring's."""
    data = SyntheticClassification(n=1024)

    def run(topo):
        model, cfg, init = _setup(topo, h=1)
        step = make_simulated_train_step(cfg, mlp_loss_fn(model))
        state = init_stacked_state(cfg, init, jax.random.key(7), topo.world_size)
        err = None
        for batch in round_batches(data, topo.world_size, h=1, batch=16, rounds=12):
            state, m = step(state, batch)
            err = float(m["consensus_error"])
        return err

    assert run(OnePeerExponentialTopology(16)) < 0.5 * run(RingTopology(16))


def test_directed_topology_rejects_faults():
    """Fault masking preserves the network mean only for symmetric W; a
    directed one-peer graph must be rejected up front (the masked matrix's
    column sums break double stochasticity — verified in review)."""
    with pytest.raises(NotImplementedError, match="SYMMETRIC"):
        GossipConfig(
            topology=OnePeerExponentialTopology(4),
            faults=FaultConfig(drop_prob=0.3),
        )


def test_symmetric_time_varying_with_faults_runs():
    """A time-varying schedule of SYMMETRIC phases composes with alive
    masking on both backends (phase dispatch + per-round alive draws)."""
    from consensusml_tpu.topology import (
        ExponentialTopology,
        TimeVaryingTopology,
    )

    topo = TimeVaryingTopology(
        [RingTopology(4), ExponentialTopology(4)], name="ring-exp-alt"
    )
    assert topo.symmetric
    model, cfg, init = _setup(topo, faults=FaultConfig(drop_prob=0.3))
    data = SyntheticClassification(n=512)
    loss_fn = mlp_loss_fn(model)
    sim_step = make_simulated_train_step(cfg, loss_fn)
    wmesh = WorkerMesh.create(topo, platform="cpu")
    col_step = make_collective_train_step(cfg, loss_fn, wmesh)
    state = init_stacked_state(cfg, init, jax.random.key(2), topo.world_size)
    sim_state, col_state = state, wmesh.shard_stacked(state)
    for batch in round_batches(data, topo.world_size, h=1, batch=16, rounds=4):
        sim_state, sm = sim_step(sim_state, batch)
        col_state, cm = col_step(col_state, batch)
    # identical per-worker rng streams => identical alive draws => same run
    assert float(sm["loss"]) == pytest.approx(float(cm["loss"]), rel=1e-4)
    assert float(sm["alive_frac"]) == pytest.approx(float(cm["alive_frac"]))
    assert jnp.isfinite(sm["consensus_error"])


def test_choco_collective_matches_simulated_onepeer():
    """CHOCO + time-varying phase dispatch: the compressed-payload
    ppermutes inside lax.switch branches must reproduce the simulated
    backend's trajectory (ChocoState threads through the branches)."""
    topo = OnePeerExponentialTopology(4)
    model, cfg, init = _setup(topo, h=1, compressor=Int8Compressor(), gamma=0.6)
    data = SyntheticClassification(n=512)
    loss_fn = mlp_loss_fn(model)
    sim_step = make_simulated_train_step(cfg, loss_fn)
    wmesh = WorkerMesh.create(topo, platform="cpu")
    col_step = make_collective_train_step(cfg, loss_fn, wmesh)
    state = init_stacked_state(cfg, init, jax.random.key(11), topo.world_size)
    sim_state, col_state = state, wmesh.shard_stacked(state)
    for batch in round_batches(data, topo.world_size, h=1, batch=16, rounds=4):
        sim_state, sm = sim_step(sim_state, batch)
        col_state, cm = col_step(col_state, batch)
    assert float(sm["loss"]) == pytest.approx(float(cm["loss"]), rel=1e-4)
    assert float(sm["consensus_error"]) == pytest.approx(
        float(cm["consensus_error"]), rel=1e-3, abs=1e-5
    )
    for a, b in zip(
        jax.tree.leaves(sim_state.params), jax.tree.leaves(col_state.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5)


def test_onepeer_with_choco_compression_converges():
    """CHOCO over a time-varying graph: loss falls, error stays bounded."""
    topo = OnePeerExponentialTopology(4)
    model, cfg, init = _setup(
        topo, h=2, compressor=Int8Compressor(), gamma=0.8
    )
    data = SyntheticClassification(n=2048)
    step = make_simulated_train_step(cfg, mlp_loss_fn(model))
    state = init_stacked_state(cfg, init, jax.random.key(3), topo.world_size)
    losses, errs = [], []
    for batch in round_batches(data, topo.world_size, h=2, batch=32, rounds=30):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
        errs.append(float(m["consensus_error"]))
    assert losses[-1] < 0.5 * losses[0]
    # int8 CHOCO converges to consensus only up to a quantization-noise
    # floor (same behavior as the static-ring CHOCO test): the error must
    # stay bounded at that floor, not grow with training
    assert errs[-1] < 1.5 * errs[0]


def test_engine_requires_step_for_time_varying():
    from consensusml_tpu.consensus import ConsensusEngine

    engine = ConsensusEngine(GossipConfig(topology=OnePeerExponentialTopology(4)))
    with pytest.raises(ValueError, match="time-varying"):
        engine.round_collective({"x": jnp.zeros(4)}, None)
