"""Native C++ runtime tests: kernel parity with the jnp reference codecs,
pipeline determinism, and end-to-end training via the native loader."""

import numpy as np
import pytest

from consensusml_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library not buildable here"
)


# ---------------------------------------------------------------------------
# kernel parity vs the jnp reference semantics
# ---------------------------------------------------------------------------


def test_quant_int8_matches_reference():
    import jax.numpy as jnp

    from consensusml_tpu.compress.reference import Int8Compressor

    rng = np.random.default_rng(0)
    x = rng.normal(size=(2048,)).astype(np.float32) * 3.0
    chunk = 256
    comp = Int8Compressor(chunk=chunk)
    ref = comp.compress(jnp.asarray(x))
    q, scales = native.quantize_int8_chunks(x.reshape(-1, chunk))
    np.testing.assert_array_equal(q.reshape(-1), np.asarray(ref.data))
    np.testing.assert_allclose(scales, np.asarray(ref.scales), rtol=0, atol=0)


def test_quant_int8_zero_chunk_roundtrip():
    x = np.zeros((2, 128), np.float32)
    x[1] = np.linspace(-1, 1, 128)
    q, scales = native.quantize_int8_chunks(x)
    assert scales[0] == 0.0
    out = native.dequantize_int8_chunks(q, scales)
    np.testing.assert_array_equal(out[0], 0.0)
    np.testing.assert_allclose(out[1], x[1], atol=1.0 / 127.0)


def test_topk_matches_lax_topk():
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(1)
    x = rng.normal(size=(513,)).astype(np.float32)
    k = 37
    vals, idx = native.topk(x, k)
    _, ref_idx = jax.lax.top_k(jnp.abs(jnp.asarray(x)), k)
    np.testing.assert_array_equal(idx, np.asarray(ref_idx))
    np.testing.assert_array_equal(vals, x[idx])


def test_topk_tie_breaking_prefers_lower_index():
    x = np.array([1.0, -1.0, 0.5, 1.0], np.float32)
    _, idx = native.topk(x, 3)
    np.testing.assert_array_equal(idx, [0, 1, 3])


def test_topk_chunks_local_indices():
    rng = np.random.default_rng(2)
    x = rng.normal(size=(4, 256)).astype(np.float32)
    vals, idx = native.topk_chunks(x, 16)
    assert vals.shape == (4, 16) and idx.shape == (4, 16)
    for c in range(4):
        v, i = native.topk(x[c], 16)
        np.testing.assert_array_equal(idx[c], i)
        np.testing.assert_array_equal(vals[c], v)


# ---------------------------------------------------------------------------
# prefetch pipeline
# ---------------------------------------------------------------------------


def _mk_loader(seed=0, depth=3, nthreads=2):
    proto = np.arange(10 * 16, dtype=np.float32).reshape(10, 16) / 100.0
    return native.NativeLoader(
        kind="classification",
        samples_per_slot=8,
        sample_floats=16,
        sample_ints=1,
        nclasses_or_vocab=10,
        noise=0.1,
        prototypes=proto,
        depth=depth,
        nthreads=nthreads,
        seed=seed,
    )


def test_loader_deterministic_across_thread_counts():
    slots_a, slots_b = [], []
    with _mk_loader(seed=7, depth=2, nthreads=1) as a:
        for _ in range(5):
            slots_a.append(a.next())
    with _mk_loader(seed=7, depth=5, nthreads=4) as b:
        for _ in range(5):
            slots_b.append(b.next())
    for (fa, ia), (fb, ib) in zip(slots_a, slots_b):
        np.testing.assert_array_equal(fa, fb)
        np.testing.assert_array_equal(ia, ib)


def test_loader_next_out_validated():
    """next(out=...) rejects mismatched reuse buffers LOUDLY — a silent
    fresh-copy fallback would defeat the staging reuse out= exists for."""
    with _mk_loader() as ld:
        good = (np.empty((8, 16), np.float32), np.empty((8, 1), np.int32))
        data, ints = ld.next(out=good)
        assert data is good[0] and ints is good[1]
        with pytest.raises(ValueError, match=r"\(data, ints\) pair"):
            ld.next(out=np.empty((8, 16), np.float32))
        with pytest.raises(ValueError, match="ndarray"):
            ld.next(out=([[0.0] * 16] * 8, good[1]))
        with pytest.raises(ValueError, match="data buffer mismatch"):
            ld.next(out=(np.empty((8, 15), np.float32), good[1]))
        with pytest.raises(ValueError, match="data buffer mismatch"):
            ld.next(out=(np.empty((8, 16), np.float64), good[1]))
        with pytest.raises(ValueError, match="ints buffer mismatch"):
            ld.next(out=(good[0], np.empty((8, 1), np.int64)))
        # u8-wire loader expects uint8 data buffers
        proto = np.arange(10 * 16, dtype=np.float32).reshape(10, 16) / 100.0
        with native.NativeLoader(
            kind="classification", samples_per_slot=8, sample_floats=16,
            sample_ints=1, nclasses_or_vocab=10, prototypes=proto, wire="u8",
        ) as u8:
            with pytest.raises(ValueError, match="data buffer mismatch"):
                u8.next(out=(np.empty((8, 16), np.float32), good[1]))
            data, _ = u8.next(out=(np.empty((8, 16), np.uint8), good[1]))
            assert data.dtype == np.uint8


def test_loader_u8_wire_requires_classification_kind():
    """cml_loader_create mirrors the create_file guard: the u8 wire
    quantizes the float payload, which only kind 0 has."""
    succ = np.zeros((10, 4), np.int32)
    with pytest.raises(RuntimeError, match="cml_loader_create failed"):
        native.NativeLoader(
            kind="lm", samples_per_slot=4, sample_floats=0, sample_ints=16,
            nclasses_or_vocab=10, successors=succ, wire="u8",
        )


def test_loader_seeds_differ():
    with _mk_loader(seed=1) as a, _mk_loader(seed=2) as b:
        fa, _ = a.next()
        fb, _ = b.next()
    assert not np.array_equal(fa, fb)


def test_loader_samples_cluster_around_prototypes():
    with _mk_loader(seed=3) as loader:
        floats, ints = loader.next()
    proto = np.arange(10 * 16, dtype=np.float32).reshape(10, 16) / 100.0
    for s in range(8):
        lab = ints[s, 0]
        assert 0 <= lab < 10
        # noise is N(0, 0.1): distance to own prototype is small
        assert np.abs(floats[s] - proto[lab]).max() < 0.6


def test_loader_prefetches_ahead():
    import time

    with _mk_loader(depth=4, nthreads=2) as loader:
        time.sleep(0.2)
        # producers should have filled the ring without any consumer pull
        assert loader.produced() >= 4


def test_native_round_batches_shapes_and_determinism():
    from consensusml_tpu.data import SyntheticClassification, native_round_batches

    ds = SyntheticClassification(n=64, image_shape=(8, 8, 1), classes=10)
    a = list(native_round_batches(ds, world_size=2, h=2, batch=4, rounds=3, seed=5))
    b = list(
        native_round_batches(
            ds, world_size=2, h=2, batch=4, rounds=3, seed=5, depth=7, nthreads=3
        )
    )
    assert a[0]["image"].shape == (2, 2, 4, 8, 8, 1)
    assert a[0]["label"].shape == (2, 2, 4)
    for ba, bb in zip(a, b):
        np.testing.assert_array_equal(np.asarray(ba["image"]), np.asarray(bb["image"]))
        np.testing.assert_array_equal(np.asarray(ba["label"]), np.asarray(bb["label"]))


def test_native_lm_batches_in_vocab_and_mlm():
    from consensusml_tpu.data import SyntheticLM, native_lm_round_batches

    ds = SyntheticLM(vocab_size=32, seq_len=16)
    (plain,) = list(native_lm_round_batches(ds, 2, 1, 4, rounds=1, seed=0))
    ids = np.asarray(plain["input_ids"])
    assert ids.shape == (2, 1, 4, 16)
    # chain never emits the reserved mask token
    assert ids.max() < ds.mask_token and ids.min() >= 0
    (mlm,) = list(
        native_lm_round_batches(ds, 2, 1, 4, rounds=1, seed=0, mlm_rate=0.3)
    )
    mask = np.asarray(mlm["mlm_mask"]).astype(bool)
    np.testing.assert_array_equal(
        np.asarray(mlm["input_ids"])[mask], ds.mask_token
    )
    np.testing.assert_array_equal(
        np.asarray(mlm["input_ids"])[~mask], np.asarray(mlm["labels"])[~mask]
    )


def test_training_step_on_native_pipeline():
    """End-to-end: one local-SGD round fed by the C++ pipeline, loss drops."""
    import jax
    import jax.numpy as jnp
    import optax

    from consensusml_tpu.consensus import GossipConfig
    from consensusml_tpu.data import SyntheticClassification, native_round_batches
    from consensusml_tpu.models import MLP, mlp_loss_fn
    from consensusml_tpu.topology import topology_from_name
    from consensusml_tpu.train import (
        LocalSGDConfig,
        init_stacked_state,
        make_simulated_train_step,
    )

    world = 4
    ds = SyntheticClassification(n=256, image_shape=(8, 8, 1))
    model = MLP(hidden=32)
    cfg = LocalSGDConfig(
        gossip=GossipConfig(topology=topology_from_name("dense", world)),
        optimizer=optax.adam(1e-2),
        h=2,
    )
    step = make_simulated_train_step(cfg, mlp_loss_fn(model))
    state = init_stacked_state(
        cfg, lambda r: model.init(r, jnp.zeros((1, 8, 8, 1)))["params"],
        jax.random.key(0), world,
    )
    losses = []
    for batch in native_round_batches(ds, world, h=2, batch=8, rounds=20, seed=0):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.7, losses


def _tiny_file_cls(n=64, hw=6):
    rng = np.random.default_rng(5)
    from consensusml_tpu.data.files import FileClassification

    images = rng.normal(size=(n, hw, hw, 1)).astype(np.float32)
    labels = rng.integers(0, 10, size=n).astype(np.int32)
    return FileClassification(
        images=images, labels=labels,
        holdout_images=images[:4], holdout_labels=labels[:4],
    )


def test_native_file_round_batches_gathers_from_shards():
    from consensusml_tpu.data import native_file_round_batches

    data = _tiny_file_cls()
    world, h, batch = 4, 2, 3
    got = list(native_file_round_batches(data, world, h, batch, rounds=2, seed=1))
    assert got[0]["image"].shape == (world, h, batch, 6, 6, 1)
    # every emitted sample must be an exact row of the worker's OWN shard
    for w in range(world):
        xs, ys = data.worker_shard(w, world)
        imgs = np.asarray(got[0]["image"][w]).reshape(-1, 36)
        labs = np.asarray(got[0]["label"][w]).reshape(-1)
        table = xs.reshape(len(xs), 36)
        for img, lab in zip(imgs, labs):
            hits = np.where((table == img).all(axis=1))[0]
            assert hits.size >= 1
            assert ys[hits[0]] == lab


def test_native_file_round_batches_deterministic():
    from consensusml_tpu.data import native_file_round_batches

    data = _tiny_file_cls()
    a = list(native_file_round_batches(data, 2, 1, 4, rounds=3, seed=7, nthreads=1))
    b = list(native_file_round_batches(data, 2, 1, 4, rounds=3, seed=7, nthreads=4))
    for x, y in zip(a, b):
        np.testing.assert_array_equal(np.asarray(x["image"]), np.asarray(y["image"]))
        np.testing.assert_array_equal(np.asarray(x["label"]), np.asarray(y["label"]))


def test_native_file_token_batches_windows():
    from consensusml_tpu.data.files import TokenFileDataset
    from consensusml_tpu.data import native_file_token_batches

    toks = (np.arange(2048, dtype=np.int32) * 3) % 251
    data = TokenFileDataset(tokens=toks, seq_len=8, vocab_size=256,
                            val_tokens=toks[:64])
    world = 4
    got = list(native_file_token_batches(data, world, 1, 4, rounds=2, seed=3))
    ids = np.asarray(got[0]["input_ids"])
    assert ids.shape == (world, 1, 4, 8)
    # every window is a contiguous run from the worker's own region
    for w in range(world):
        lo, hi = data.worker_region(w, world)
        for row in ids[w].reshape(-1, 8):
            starts = np.where(toks[lo:hi] == row[0])[0]
            assert any(
                np.array_equal(row, toks[lo + s : lo + s + 8]) for s in starts
            ), (w, row)


def test_native_file_token_batches_mlm_and_determinism():
    from consensusml_tpu.data.files import TokenFileDataset
    from consensusml_tpu.data import native_file_token_batches

    toks = np.full(1024, 3, np.int32)
    data = TokenFileDataset(tokens=toks, seq_len=8, vocab_size=16,
                            val_tokens=toks[:16])
    a = list(native_file_token_batches(data, 2, 1, 2, rounds=2, seed=9,
                                       mlm_rate=0.5, nthreads=1))
    b = list(native_file_token_batches(data, 2, 1, 2, rounds=2, seed=9,
                                       mlm_rate=0.5, nthreads=3))
    for x, y in zip(a, b):
        for key in ("input_ids", "labels", "mlm_mask"):
            np.testing.assert_array_equal(np.asarray(x[key]), np.asarray(y[key]))
    masked = np.asarray(a[0]["mlm_mask"]) > 0
    assert (np.asarray(a[0]["input_ids"])[masked] == data.mask_token).all()


def test_native_loader_rejects_too_small_token_table():
    from consensusml_tpu.native import NativeLoader

    with pytest.raises(RuntimeError, match="create_file failed"):
        NativeLoader(
            kind="file_lm", samples_per_slot=4, sample_floats=0,
            sample_ints=16, world=4, tokens=np.zeros(64, np.int32),
        )


def test_native_file_token_batches_uint16_memmap(tmp_path):
    """uint16 token files flow through uncopied; ids match the int32 path."""
    from consensusml_tpu.data.files import TokenFileDataset
    from consensusml_tpu.data import native_file_token_batches

    raw = ((np.arange(1024) * 5) % 60000).astype(np.uint16)
    p = tmp_path / "t.bin"
    raw.tofile(p)
    mm = np.memmap(p, dtype=np.uint16, mode="r")
    d16 = TokenFileDataset(tokens=mm, seq_len=8, vocab_size=1 << 16,
                           val_tokens=mm[:16])
    d32 = TokenFileDataset(tokens=raw.astype(np.int32), seq_len=8,
                           vocab_size=1 << 16, val_tokens=raw[:16].astype(np.int32))
    a = list(native_file_token_batches(d16, 2, 1, 3, rounds=2, seed=11))
    b = list(native_file_token_batches(d32, 2, 1, 3, rounds=2, seed=11))
    for x, y in zip(a, b):
        np.testing.assert_array_equal(
            np.asarray(x["input_ids"]), np.asarray(y["input_ids"])
        )
    assert np.asarray(a[0]["input_ids"]).dtype == np.int32


def test_native_start_seq_resumes_stream_exactly():
    """start=N reproduces the same batches a fresh run yields at round N
    — in O(1), not by discarding N slots."""
    from consensusml_tpu.data import native_round_batches
    from consensusml_tpu.data.synthetic import SyntheticClassification

    data = SyntheticClassification(n=64, image_shape=(4, 4, 1))
    full = list(native_round_batches(data, 2, 1, 4, rounds=5, seed=3))
    tail = list(native_round_batches(data, 2, 1, 4, rounds=2, seed=3, start=3))
    for a, b in zip(full[3:], tail):
        np.testing.assert_array_equal(np.asarray(a["image"]), np.asarray(b["image"]))
        np.testing.assert_array_equal(np.asarray(a["label"]), np.asarray(b["label"]))


def test_loader_u8_wire_quantizes_f32_stream():
    """u8 wire = clip((x + qoff) * qscale) of the SAME deterministic f32
    stream (labels identical, values within half a quant step), shipped
    as uint8 — the 1/4-wire mode the fed bench measures."""
    proto = np.arange(10 * 16, dtype=np.float32).reshape(10, 16) / 100.0
    kw = dict(
        kind="classification", samples_per_slot=8, sample_floats=16,
        sample_ints=1, nclasses_or_vocab=10, noise=0.1, prototypes=proto,
        seed=11,
    )
    with native.NativeLoader(**kw) as a, native.NativeLoader(
        **kw, wire="u8", qscale=32.0, qoff=4.0
    ) as b:
        f, fi = a.next()
        u, ui = b.next()
    assert u.dtype == np.uint8
    np.testing.assert_array_equal(fi, ui)
    want = np.clip((f + 4.0) * 32.0, 0, 255)
    np.testing.assert_allclose(u.astype(np.float32), want, atol=0.5)
    # device-side dequant recovers the f32 values to half a quant step
    np.testing.assert_allclose(
        u.astype(np.float32) / 32.0 - 4.0, f, atol=0.5 / 32.0 + 1e-6
    )


def test_loader_u8_wire_file_kind():
    from consensusml_tpu.data.native_pipeline import native_file_round_batches

    class _DS:
        n = 8
        image_shape = (4, 4, 1)
        images = (np.arange(8 * 16, dtype=np.float32).reshape(8, 16) % 7) / 7.0
        labels = np.arange(8, dtype=np.int32)

    f32 = list(native_file_round_batches(_DS(), 2, 1, 2, rounds=3, seed=5))
    u8 = list(
        native_file_round_batches(
            _DS(), 2, 1, 2, rounds=3, seed=5, wire="u8", qscale=255.0, qoff=0.0
        )
    )
    for a, b in zip(f32, u8):
        assert np.asarray(b["image"]).dtype == np.uint8
        np.testing.assert_array_equal(
            np.asarray(a["label"]), np.asarray(b["label"])
        )
        # the table values are k/7 with k<7, so /255 quantization is
        # lossless to half a step
        np.testing.assert_allclose(
            np.asarray(b["image"]).astype(np.float32) / 255.0,
            np.asarray(a["image"]),
            atol=0.5 / 255.0 + 1e-6,
        )


def test_loader_next_out_reuse_matches_fresh_copies():
    """next(out=...) fills caller buffers with the identical stream (the
    rotating-buffer fast path the pipeline iterators use)."""
    with _mk_loader(seed=9) as a, _mk_loader(seed=9) as b:
        outs = (np.empty((8, 16), np.float32), np.empty((8, 1), np.int32))
        for _ in range(4):
            ff, fi = a.next()
            rf, ri = b.next(out=outs)
            assert rf is outs[0] and ri is outs[1]
            np.testing.assert_array_equal(ff, rf)
            np.testing.assert_array_equal(fi, ri)
