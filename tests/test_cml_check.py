"""cml-check static-analysis suite: known-bad fixtures must be caught,
the repo itself must be clean (modulo the checked-in baseline).

Run standalone with ``pytest -m analysis``; part of tier-1 (not slow).
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from consensusml_tpu.analysis import (
    Finding,
    load_baseline,
    split_suppressed,
)
from consensusml_tpu.analysis import host_sync, locks, schedule
from consensusml_tpu.consensus import ConsensusEngine, GossipConfig
from consensusml_tpu.topology import RingTopology, Shift

pytestmark = pytest.mark.analysis

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLI = os.path.join(REPO, "tools", "cml_check.py")


def _rules(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# host-sync lint: known-bad snippets
# ---------------------------------------------------------------------------


def _lint(src: str):
    return host_sync.lint_source(textwrap.dedent(src), "fixture.py")


def test_sync_in_jitted_function_is_flagged():
    fs = _lint(
        """
        import jax

        @jax.jit
        def step(x):
            y = x + 1
            jax.block_until_ready(y)
            return y
        """
    )
    assert "sync-in-traced" in _rules(fs)


def test_numpy_in_scan_body_is_flagged():
    fs = _lint(
        """
        import jax
        import numpy as np

        def outer(xs):
            def body(carry, x):
                return carry + np.asarray(x), None
            return jax.lax.scan(body, 0.0, xs)
        """
    )
    assert "numpy-in-traced" in _rules(fs)


def test_time_in_shard_mapped_function_is_flagged():
    fs = _lint(
        """
        import time
        import jax

        def per_worker(x):
            t0 = time.time()
            return x * t0

        def build(mesh, P):
            return jax.shard_map(per_worker, mesh=mesh, in_specs=P, out_specs=P)
        """
    )
    assert "time-in-traced" in _rules(fs)


def test_branch_on_traced_param_is_flagged_but_static_forms_are_not():
    fs = _lint(
        """
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(x, state, cfg):
            if x > 0:            # BAD: tracer truthiness
                x = x - 1
            if state is None:    # ok: presence check
                x = x + 1
            if cfg.h > 2:        # ok: attribute access = static config
                x = x * 2
            if len(x) > 1:       # ok: static shape info
                x = x + 2
            return x
        """
    )
    hits = [f for f in fs if f.rule == "branch-on-traced"]
    assert [f.detail for f in hits] == ["x"]


def test_item_in_vmapped_function_is_flagged():
    fs = _lint(
        """
        import jax

        def f(x):
            return x.item()

        g = jax.vmap(f)
        """
    )
    assert "item-in-traced" in _rules(fs)


def test_nested_and_called_functions_inherit_tracedness():
    fs = _lint(
        """
        import jax

        def helper(x):
            jax.device_get(x)   # traced via call from `step`
            return x

        @jax.jit
        def step(x):
            def inner(y):
                return y.tolist()   # traced via nesting
            return helper(x)
        """
    )
    rules = _rules(fs)
    assert "sync-in-traced" in rules and "item-in-traced" in rules


def test_host_side_sync_is_inventoried_not_traced_rule():
    fs = _lint(
        """
        import jax

        def save(state):
            return jax.device_get(state)
        """
    )
    assert _rules(fs) == ["host-sync"]
    assert fs[0].symbol == "save"


def test_clean_traced_code_has_no_findings():
    fs = _lint(
        """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x, rng):
            y = jnp.where(x > 0, x, -x)
            if rng is None:
                return y
            return y + jax.random.normal(rng, y.shape)
        """
    )
    assert fs == []


def test_tree_map_is_not_mistaken_for_lax_map():
    fs = _lint(
        """
        import jax

        def place(batch):
            return jax.tree.map(lambda x: x if x.ndim else x, batch)
        """
    )
    assert fs == []


# ---------------------------------------------------------------------------
# lock-discipline lint
# ---------------------------------------------------------------------------


def _lint_locks(src: str):
    return locks.lint_source(textwrap.dedent(src), "fixture.py")


_LOCK_FIXTURE = """
    import threading
    from consensusml_tpu.analysis import guarded_by

    @guarded_by("_lock", "_value", "_count")
    class Shared:
        def __init__(self):
            self._lock = threading.Lock()
            self._value = 0        # ok: __init__ exempt
            self._count = 0

        def good(self):
            with self._lock:
                self._value += 1
                return self._count

        def bad_write(self):
            self._value += 1       # finding

        def bad_read(self):
            return self._value     # finding

        def bad_closure(self):
            with self._lock:
                def cb():
                    return self._count   # finding: closure escapes
                return cb

        def unannotated_ok(self):
            return id(self._lock)
"""


def test_lock_lint_flags_unguarded_access():
    fs = _lint_locks(_LOCK_FIXTURE)
    got = {(f.rule, f.symbol, f.detail) for f in fs}
    assert ("unguarded-write", "Shared.bad_write", "_value") in got
    assert ("unguarded-read", "Shared.bad_read", "_value") in got
    assert (
        "unguarded-read", "Shared.bad_closure.<locals>.cb", "_count"
    ) in got
    # nothing else: __init__ and with-lock accesses are clean
    assert len(fs) == 3


def test_lock_lint_flags_escaping_lambda_even_under_lock():
    """A lambda is a closure: written under the lock, handed to a
    thread, run without it — must be analyzed with an empty lock set
    exactly like a nested def."""
    fs = _lint_locks(
        """
        from consensusml_tpu.analysis import guarded_by

        @guarded_by("_lock", "_value")
        class Shared:
            def leak(self, spawn):
                with self._lock:
                    return spawn(target=lambda: self._value + 1)
        """
    )
    assert [(f.rule, f.symbol) for f in fs] == [
        ("unguarded-read", "Shared.leak.<locals>.<lambda>")
    ]


def test_lock_lint_ignores_classes_without_annotation():
    fs = _lint_locks(
        """
        class Plain:
            def touch(self):
                self._value = 1
        """
    )
    assert fs == []


def test_guarded_by_records_contract_at_runtime():
    from consensusml_tpu.analysis import guarded_by

    @guarded_by("_lock", "_a")
    @guarded_by("_other", "_b")
    class C:
        pass

    assert C.__guarded_by__ == {"_a": "_lock", "_b": "_other"}


def test_repo_threaded_modules_are_annotated_and_clean():
    """The threaded host-side modules carry @guarded_by and pass the
    lint — including the ISSUE 14 additions (hot-swap watcher, serve
    front-end, metrics HTTP server)."""
    for rel in (
        "consensusml_tpu/obs/metrics.py",
        "consensusml_tpu/obs/httpd.py",
        "consensusml_tpu/data/prefetch.py",
        "consensusml_tpu/native/__init__.py",
        "consensusml_tpu/utils/watchdog.py",
        "consensusml_tpu/serve/pool/hotswap.py",
        "consensusml_tpu/serve/server.py",
    ):
        path = os.path.join(REPO, rel)
        fs = locks.lint_file(path, REPO)
        assert fs == [], f"{rel}: {[f.render() for f in fs]}"
        src = open(path).read()
        assert "guarded_by(" in src, f"{rel} lost its annotations"


def test_bare_acquire_is_flagged():
    """ISSUE 14 satellite: the blind spot the old module docstring
    admitted — a bare acquire/release pair on a class's lock attr is now
    a finding (the in-tree occurrence in obs/httpd.py was converted to a
    with-guarded flag)."""
    fs = _lint_locks(
        """
        import threading

        class S:  # note: bare-acquire needs no @guarded_by annotation
            def __init__(self):
                self._lock = threading.Lock()

            def bad(self):
                self._lock.acquire()
                try:
                    return 1
                finally:
                    self._lock.release()

            def try_bad(self):
                if not self._lock.acquire(blocking=False):
                    return None
                self._lock.release()
        """
    )
    assert _rules(fs) == ["bare-acquire"]
    assert {f.symbol for f in fs} == {"S.bad", "S.try_bad"}
    # both calls in one method share one finding id (baseline granularity)
    assert len({f.id for f in fs if f.symbol == "S.bad"}) == 1


def test_guarded_escape_rules():
    """Escape analysis: returning/yielding a bare reference to a guarded
    MUTABLE leaks it out of the lock; copies, scalars and the ownership-
    transfer pattern stay clean."""
    fs = _lint_locks(
        """
        import threading
        from collections import deque
        from consensusml_tpu.analysis import guarded_by

        @guarded_by("_lock", "_items", "_ring", "_n")
        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []
                self._ring = deque(maxlen=8)
                self._n = 0

            def leak(self):
                with self._lock:
                    return self._items          # finding

            def leak_gen(self):
                with self._lock:
                    yield self._ring            # finding

            def leak_alias(self):
                with self._lock:
                    out = self._items           # alias under lock
                return out                      # finding

            def ok_copy(self):
                with self._lock:
                    return list(self._items)

            def ok_transfer(self):
                with self._lock:
                    out, self._items = self._items, []
                return out

            def ok_scalar(self):
                with self._lock:
                    return self._n
        """
    )
    got = {(f.rule, f.symbol) for f in fs}
    assert got == {
        ("guarded-escape", "S.leak"),
        ("guarded-escape", "S.leak_gen"),
        ("guarded-alias-escape", "S.leak_alias"),
    }


def test_alias_rebound_to_copy_is_not_an_escape():
    """`x = self._items` under the lock then `x = list(x)` before the
    return — the very fix the escape rule recommends — is clean."""
    fs = _lint_locks(
        """
        import threading
        from consensusml_tpu.analysis import guarded_by

        @guarded_by("_lock", "_items")
        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []

            def snapshot(self):
                with self._lock:
                    out = self._items
                out = list(out)
                return out
        """
    )
    assert fs == [], [f.render() for f in fs]


# ---------------------------------------------------------------------------
# threads pass: spawn/handler inventory (ISSUE 14)
# ---------------------------------------------------------------------------


def _threads_run(tmp_path, code: str, doc: str):
    from consensusml_tpu.analysis import threads

    src = tmp_path / "mod.py"
    src.write_text(textwrap.dedent(code))
    docp = tmp_path / "threads.md"
    docp.write_text(textwrap.dedent(doc))
    return threads.run(
        str(tmp_path), py_files=[str(src)], doc_path=str(docp),
        report_stale=True,
    )


def test_unregistered_thread_is_flagged(tmp_path):
    """The acceptance bad fixture: a thread the inventory does not list
    is a finding; a documented one is clean."""
    fs = _threads_run(
        tmp_path,
        """
        import threading

        class W:
            def start(self):
                t = threading.Thread(
                    target=self._run, name="known-worker", daemon=True
                )
                u = threading.Thread(target=self._sneak, daemon=True)
                t.start(); u.start()
        """,
        "| `mod.py:W.start:known-worker` | yes | joined | documented |\n",
    )
    assert _rules(fs) == ["undocumented-thread"]
    (f,) = fs
    assert f.detail == "self._sneak" and f.symbol == "W.start"


def test_unregistered_handler_and_stale_doc_row(tmp_path):
    fs = _threads_run(
        tmp_path,
        """
        import signal

        def arm():
            signal.signal(signal.SIGTERM, lambda s, f: None)
        """,
        "| `mod.py:gone_fn:SIGUSR1` | - | | a thread of the past |\n",
    )
    assert _rules(fs) == ["stale-thread-doc", "undocumented-handler"]
    assert {f.detail for f in fs} == {"SIGTERM", "mod.py:gone_fn:SIGUSR1"}


def test_daemon_mismatch_is_flagged(tmp_path):
    fs = _threads_run(
        tmp_path,
        """
        import threading

        def spawn():
            threading.Thread(target=spin, name="w", daemon=False).start()
        """,
        "| `mod.py:spawn:w` | yes | joined | drifted |\n",
    )
    assert _rules(fs) == ["daemon-mismatch"]


def test_thread_spawner_with_undeclared_lock_contract_is_flagged(tmp_path):
    """A class that spawns a thread and owns a Lock but carries no
    @guarded_by: the sharing is real, the contract is invisible."""
    fs = _threads_run(
        tmp_path,
        """
        import threading

        class Undeclared:
            def __init__(self):
                self._lock = threading.Lock()
                self._t = threading.Thread(
                    target=self._run, name="undeclared", daemon=True
                )

        class Declared:
            pass
        """,
        "| `mod.py:Undeclared.__init__:undeclared` | yes | joined | ok |\n",
    )
    assert _rules(fs) == ["unannotated-thread-state"]
    assert fs[0].detail == "_lock"


def test_repo_thread_inventory_is_complete():
    """Acceptance: every thread/handler in the package + entry points is
    documented in docs/threads.md, no stale rows, no undeclared lock
    contracts — with NO baseline help."""
    from consensusml_tpu.analysis import threads

    fs = threads.check_repo(REPO)
    assert fs == [], [f.render() for f in fs]


# ---------------------------------------------------------------------------
# lockorder pass: static deadlock detection (ISSUE 14)
# ---------------------------------------------------------------------------


_ABBA_FIXTURE = """
    import threading

    class Registry:
        def __init__(self):
            self._lock = threading.Lock()
            self._w = Watcher()

        def poke(self):
            with self._lock:
                pass

        def scrape(self):
            with self._lock:
                self._w.take()      # holds Registry._lock -> Watcher._lock

    class Watcher:
        def __init__(self):
            self._lock = threading.Lock()
            self._reg = Registry()

        def take(self):
            with self._lock:
                pass

        def publish(self):
            with self._lock:
                self._reg.poke()    # holds Watcher._lock -> Registry._lock
"""


def test_abba_two_class_deadlock_is_detected_statically():
    """The acceptance bad fixture: opposite-order acquisition across two
    classes, composed through typed attributes and the call graph — a
    lock-cycle finding with no thread ever run."""
    from consensusml_tpu.analysis import lockorder

    model = lockorder.analyze_sources(
        [("fx.py", textwrap.dedent(_ABBA_FIXTURE))]
    )
    assert ("Registry._lock", "Watcher._lock") in model.edges
    assert ("Watcher._lock", "Registry._lock") in model.edges
    fs = model.findings()
    assert _rules(fs) == ["lock-cycle"]
    # canonical, line-number-free cycle detail => stable baseline id
    assert fs[0].detail == "Registry._lock->Watcher._lock->Registry._lock"
    assert fs[0].id == (
        "lockorder:lock-cycle:fx.py:<graph>:"
        "Registry._lock->Watcher._lock->Registry._lock"
    )


def test_branchy_scc_still_yields_a_witness_cycle():
    """A cycle inside a branchy SCC (where a greedy min-successor walk
    dead-ends) must still produce a lock-cycle finding, not an internal
    error: edges A->B, B->C, B->D, C->B, D->A."""
    from consensusml_tpu.analysis import lockorder

    model = lockorder.LockModel()
    for a, b in [("A", "B"), ("B", "C"), ("B", "D"), ("C", "B"),
                 ("D", "A")]:
        model.add_edge(a, b, "fx.py", 1, f"{a}->{b}")
    fs = model.findings()
    assert _rules(fs) == ["lock-cycle"], [f.render() for f in fs]
    # the witness is a real cycle through the graph's edges
    cyc = fs[0].detail.split("->")
    assert cyc[0] == cyc[-1]
    for x, y in zip(cyc, cyc[1:]):
        assert (x, y) in model.edges, (x, y)


def test_plain_lock_self_reentry_is_a_deadlock():
    from consensusml_tpu.analysis import lockorder

    model = lockorder.analyze_sources(
        [(
            "fx.py",
            textwrap.dedent(
                """
                import threading

                class C:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def outer(self):
                        with self._lock:
                            self.inner()

                    def inner(self):
                        with self._lock:
                            pass
                """
            ),
        )]
    )
    assert _rules(model.findings()) == ["self-deadlock"]


def test_rlock_reentry_is_exempt_self_loop():
    """The obs/requests.py idiom: _finish_locked re-enters the RLock the
    caller already holds — modeled as a re-entry, not a deadlock."""
    from consensusml_tpu.analysis import lockorder

    model = lockorder.analyze_sources(
        [(
            "fx.py",
            textwrap.dedent(
                """
                import threading

                class R:
                    def __init__(self):
                        self._lock = threading.RLock()

                    def finish(self):
                        with self._lock:
                            self._finish_locked()

                    def _finish_locked(self):
                        with self._lock:
                            pass
                """
            ),
        )]
    )
    assert model.findings() == []
    assert "R._lock" in model.reentries


def test_repo_lock_graph_is_acyclic_and_leaf_disciplined():
    """Acceptance: the package lock graph has NO cross-lock edges (every
    lock is leaf-level — nothing acquires one lock while holding
    another) and the only nesting is the request registry's documented
    RLock re-entry. A future edge is fine; a cycle never is."""
    from consensusml_tpu.analysis import lockorder

    model = lockorder.static_model(REPO)
    assert model.findings() == [], [
        f.render() for f in model.findings()
    ]
    assert model.edges == {}, sorted(model.edges)
    assert "RequestTraceRegistry._lock" in model.reentries


# ---------------------------------------------------------------------------
# schedule verifier
# ---------------------------------------------------------------------------

LEAVES = [((64, 8), "float32"), ((32,), "bfloat16"), ((513,), "float32")]


@pytest.mark.parametrize("bucket_bytes", [0, 4 * 2**20])
@pytest.mark.parametrize("name", sorted(schedule.builtin_topologies(8)))
def test_every_topology_schedule_verifies(name, bucket_bytes):
    """Satellite: every shipped topology x bucket_bytes in {0 (per-leaf),
    4MiB} materializes a deadlock-free, bijective schedule — exact and
    (static graphs) compressed."""
    from consensusml_tpu.compress import topk_int8_compressor

    topo = schedule.builtin_topologies(8)[name]
    bb = bucket_bytes or None  # 0 == per-leaf wire (GossipConfig contract)
    engines = [ConsensusEngine(GossipConfig(topology=topo, bucket_bytes=bb))]
    if not topo.is_time_varying:
        engines.append(
            ConsensusEngine(
                GossipConfig(
                    topology=topo,
                    compressor=topk_int8_compressor(
                        ratio=0.1, chunk=128, impl="jnp"
                    ),
                    gamma=0.5,
                    bucket_bytes=bb,
                )
            )
        )
    for eng in engines:
        fs = schedule.verify_engine(eng, LEAVES, source=f"test:{name}")
        assert fs == [], [f.render() for f in fs]


class _AsymmetricRing(RingTopology):
    """Deliberately broken: rank 0 gossips with different offsets than
    everyone else — the static form of a rank-divergent ppermute."""

    def rank_shifts(self, rank):
        if rank == 0:
            return (Shift(0, +3, 1.0 / 3), Shift(0, -1, 1.0 / 3))
        return self.shifts


def test_asymmetric_topology_is_reported_as_deadlock_statically():
    """The acceptance fixture: no mesh, no collective, no device — the
    deadlock is proven from the materialized schedules alone."""
    eng = ConsensusEngine(GossipConfig(topology=_AsymmetricRing(8)))
    fs = schedule.verify_engine(eng, LEAVES, source="test:asym")
    rules = _rules(fs)
    assert "deadlock-endpoint-mismatch" in rules
    # and the lint names both wedged endpoints of the first bad transfer
    details = {f.detail for f in fs if f.rule == "deadlock-endpoint-mismatch"}
    assert any(d.startswith("pos0:r0->") for d in details)


def test_rank_dependent_collective_count_is_a_deadlock():
    class ExtraShift(RingTopology):
        def rank_shifts(self, rank):
            if rank == 3:
                return self.shifts + (Shift(0, +2, 0.0),)
            return self.shifts

    eng = ConsensusEngine(GossipConfig(topology=ExtraShift(8)))
    fs = schedule.verify_engine(eng, LEAVES, source="test:count")
    assert _rules(fs) == ["deadlock-op-count"]


def test_non_bijective_perm_is_flagged():
    ops = [
        [
            schedule.RankOp(
                "ppermute", "workers", "leaf0", (8,), "float32",
                send_to=0 if r < 2 else r, recv_from=(r + 1) % 4,
            )
        ]
        for r in range(4)
    ]
    fs = schedule.verify_schedules(ops, source="test:nonbij", topology=None)
    assert "perm-not-bijective" in _rules(fs)


def test_payload_mismatch_across_ranks_is_flagged():
    mk = lambda dtype: [
        schedule.RankOp(
            "ppermute", "workers", "leaf0", (8,), dtype,
            send_to=(r + 1) % 4, recv_from=(r - 1) % 4,
        )
        for r in range(4)
    ]
    ops = [[op] for op in mk("float32")]
    ops[2] = [mk("bfloat16")[2]]  # rank 2 ships a different dtype
    fs = schedule.verify_schedules(ops, source="test:dtype", topology=None)
    assert "deadlock-op-mismatch" in _rules(fs)


def test_schedule_matches_engine_bucketing():
    """The materializer uses the engine's own plan: shrinking
    bucket_bytes must grow the per-shift op count accordingly."""
    topo = RingTopology(4)
    leaves = [((4096,), "float32"), ((4096,), "float32")]
    ops_for = lambda bb: len(
        schedule.materialize_schedules(
            ConsensusEngine(
                GossipConfig(topology=topo, bucket_bytes=bb)
            ),
            leaves,
        )[0]
    )
    assert ops_for(1 << 20) == 2  # one bucket x two shifts
    assert ops_for(8 * 1024) == 4  # two buckets x two shifts
    assert ops_for(None) == 4  # per-leaf x two shifts


# ---------------------------------------------------------------------------
# jaxpr contracts
# ---------------------------------------------------------------------------


def test_jaxpr_contracts_mnist_and_gpt2_clean():
    from consensusml_tpu.analysis import jaxpr_contracts

    for name in ("mnist_mlp", "gpt2_topk"):
        fs = jaxpr_contracts.check_config(name)
        assert fs == [], [f.render() for f in fs]


def test_jaxpr_decode_contracts_run_on_lm_configs_only():
    """The serving decode step carries its own contracts (no host
    callbacks, no f64, zero step-over-step recompiles) on the causal-LM
    configs; non-LM configs have no decode path and are skipped."""
    from consensusml_tpu import configs
    from consensusml_tpu.analysis.jaxpr_contracts import _check_decode_jaxpr

    for name in ("gpt2_topk", "llama_lora"):
        fs = _check_decode_jaxpr(name, configs.build(name))
        assert fs == [], [f.render() for f in fs]
    assert _check_decode_jaxpr("mnist_mlp", configs.build("mnist_mlp")) == []


def test_fused_wire_contract_is_clean():
    """ISSUE 9 CI satellite: the fused one-pass wire traces exactly one
    pallas_call per bucket per kernel stage (encode+decode on ppermute
    topologies, encode-only on psum) and its traced ppermute count still
    matches the schedule verifier's model."""
    from consensusml_tpu.analysis import jaxpr_contracts

    fs = jaxpr_contracts.check_fused_wire()
    assert fs == [], [f.render() for f in fs]


def test_fused_wire_contract_catches_unfused_fallback():
    """The fused-active rule fires when the fused wire silently falls
    back: the kernel-count rule fires when the traced program's
    pallas_call count drifts from the per-bucket contract (simulated
    here by lying to the checker about the expected count via a codec
    that never fuses — the fused-active finding is the canary)."""
    import consensusml_tpu.compress as C
    import consensusml_tpu.analysis.jaxpr_contracts as jc

    # a codec class whose instances refuse to fuse: auto-mode engines
    # silently keep the two-step path, which the contract must flag
    class NoFuse(C.PallasInt8Compressor):
        def fused_wire(self):
            return None

    real = C.PallasInt8Compressor
    C.PallasInt8Compressor = NoFuse
    try:
        fs = jc.check_fused_wire()
    finally:
        C.PallasInt8Compressor = real
    assert "fused-active" in _rules(fs), [f.render() for f in fs]


def test_jaxpr_callback_detector_sees_callbacks():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from consensusml_tpu.analysis.jaxpr_contracts import count_primitives

    def bad(x):
        y = jax.pure_callback(
            lambda v: np.asarray(v) * 2,
            jax.ShapeDtypeStruct(x.shape, x.dtype),
            x,
        )
        return jnp.sum(y)

    counts = count_primitives(jax.make_jaxpr(bad)(jnp.ones((4,))))
    assert any("callback" in k for k in counts), counts


# ---------------------------------------------------------------------------
# baseline mechanics
# ---------------------------------------------------------------------------


def test_baseline_suppression_and_stale_reporting(tmp_path):
    f1 = Finding("host-sync", "host-sync", "a.py", "f", "device_get", "m", 1)
    f2 = Finding("host-sync", "host-sync", "b.py", "g", "device_get", "m", 2)
    bl = tmp_path / "baseline"
    bl.write_text(
        f"# comment\n{f1.id}  # inline comment\nhost-sync:gone:entry:x:y\n"
    )
    active, suppressed, stale = split_suppressed(
        [f1, f2], load_baseline(str(bl))
    )
    assert [f.id for f in active] == [f2.id]
    assert [f.id for f in suppressed] == [f1.id]
    assert stale == ["host-sync:gone:entry:x:y"]


def test_finding_id_is_line_number_stable():
    a = Finding("locks", "unguarded-read", "m.py", "C.f", "_x", "msg", 10)
    b = Finding("locks", "unguarded-read", "m.py", "C.f", "_x", "msg", 99)
    assert a.id == b.id


# ---------------------------------------------------------------------------
# docs-drift pass: code families vs docs/observability.md
# ---------------------------------------------------------------------------


def _drift(tmp_path, code: str, doc: str):
    from consensusml_tpu.analysis import docs_drift

    src = tmp_path / "mod.py"
    src.write_text(textwrap.dedent(code))
    docp = tmp_path / "observability.md"
    docp.write_text(textwrap.dedent(doc))
    return docs_drift.run(
        str(tmp_path), py_files=[str(src)], doc_path=str(docp)
    )


def test_docs_drift_undocumented_metric_is_flagged(tmp_path):
    fs = _drift(
        tmp_path,
        """
        def f(reg):
            reg.counter("consensusml_widget_total", "widgets")
            reg.gauge("consensusml_depth", "documented one")
        """,
        "| `consensusml_depth` | gauge | documented |\n",
    )
    assert _rules(fs) == ["undocumented-metric"]
    (f,) = fs
    assert f.detail == "consensusml_widget_total" and f.symbol == "f"


def test_docs_drift_stale_doc_entry_is_flagged(tmp_path):
    fs = _drift(
        tmp_path,
        """
        def f(reg):
            reg.counter("consensusml_widget_total")
        """,
        "`consensusml_widget_total` and `consensusml_gone_total`\n",
    )
    assert _rules(fs) == ["stale-doc-metric"]
    assert fs[0].detail == "consensusml_gone_total"


def test_docs_drift_dynamic_prefix_exempts_doc_entries(tmp_path):
    # f-string-composed families: the literal prefix marks the namespace
    # as dynamically emitted, so doc rows under it are not stale — but
    # the bare consensusml_ prefix must NOT blanket-exempt everything
    fs = _drift(
        tmp_path,
        """
        def f(reg, kind):
            reg.counter(f"consensusml_swarm_{kind}_total")
        """,
        "`consensusml_swarm_join_total` but also `consensusml_vanished`\n",
    )
    assert _rules(fs) == ["stale-doc-metric"]
    assert fs[0].detail == "consensusml_vanished"


def test_docs_drift_repo_is_clean():
    """The repo's metric schema agrees with docs/observability.md —
    modulo the baselined dynamically-composed families (engine
    telemetry gauges, MetricsLogger per-field gauges)."""
    from consensusml_tpu.analysis import docs_drift

    findings = docs_drift.check_repo(REPO)
    baseline = load_baseline(os.path.join(REPO, ".cml-check-baseline"))
    active, suppressed, _stale = split_suppressed(findings, baseline)
    assert active == []
    # every suppression is a stale-doc entry for a dynamic family, never
    # an undocumented emission
    assert all(f.rule == "stale-doc-metric" for f in suppressed)


# ---------------------------------------------------------------------------
# the CLI gate (acceptance criteria)
# ---------------------------------------------------------------------------


def _run_cli(*args, timeout=240):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # the CLI sets its own device count
    return subprocess.run(
        [sys.executable, CLI, *args],
        capture_output=True, text=True, cwd=REPO, timeout=timeout, env=env,
    )


def test_cli_all_exits_zero_on_repo():
    """`python tools/cml_check.py --all` is the tier-1 gate: the repo is
    clean under the checked-in baseline, machine-readably."""
    res = _run_cli("--all", "--json", "-")
    assert res.returncode == 0, res.stdout + res.stderr
    doc = json.loads(res.stdout)
    assert doc["ok"] is True
    assert doc["findings"] == []
    assert doc["counts"]["suppressed"] >= 1  # the intentional-sync inventory
    assert doc["counts"]["stale"] == 0, doc["stale_baseline"]
    assert set(doc["passes"]) == {
        "host-sync", "locks", "threads", "lockorder", "docs-drift",
        "lifecycle", "model", "schedule", "jaxpr",
    }
    # per-pass wall time rides the JSON; the AST passes hold their
    # absolute budget (<2 s each, gated in tools/bench_diff.py's spec;
    # the exhaustive model checker gets 30 s)
    secs = doc["pass_seconds"]
    for name in ("host-sync", "locks", "threads", "lockorder", "docs-drift",
                 "lifecycle"):
        assert secs[name] < 2.0, (name, secs)
    assert secs["model"] < 30.0, secs


def test_cli_exits_nonzero_on_threads_bad_fixture(tmp_path):
    """An undocumented thread in a --paths-restricted tree fails the
    gate without dragging the repo inventory's rows in as stale."""
    bad = tmp_path / "bad.py"
    bad.write_text(
        textwrap.dedent(
            """
            import threading

            def spawn():
                threading.Thread(target=spawn, daemon=True).start()
            """
        )
    )
    res = _run_cli(
        "--threads", "--paths", str(bad), "--json", "-", timeout=120,
    )
    assert res.returncode == 1, res.stdout + res.stderr
    doc = json.loads(res.stdout)
    assert [f["rule"] for f in doc["findings"]] == ["undocumented-thread"]
    assert doc["stale_baseline"] == []


def test_cli_path_restricted_run_does_not_report_foreign_stale(tmp_path):
    """`--paths` narrowing must not flag baseline entries for files the
    run never scanned as stale (a developer would prune live
    suppressions)."""
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    res = _run_cli(
        "--host-sync", "--paths", str(clean), "--json", "-", timeout=120,
    )
    assert res.returncode == 0, res.stdout + res.stderr
    doc = json.loads(res.stdout)
    assert doc["stale_baseline"] == []


def test_cli_exits_nonzero_on_lockorder_bad_fixture(tmp_path):
    """The ABBA tree fails the gate through the CLI too."""
    bad = tmp_path / "abba.py"
    bad.write_text(textwrap.dedent(_ABBA_FIXTURE))
    res = _run_cli(
        "--lockorder", "--paths", str(tmp_path), "--baseline", "none",
        "--json", "-", timeout=120,
    )
    assert res.returncode == 1, res.stdout + res.stderr
    doc = json.loads(res.stdout)
    assert any(f["rule"] == "lock-cycle" for f in doc["findings"])


def test_cli_exits_nonzero_on_bad_fixture(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        textwrap.dedent(
            """
            import jax

            @jax.jit
            def step(x):
                jax.block_until_ready(x)
                return x
            """
        )
    )
    res = _run_cli(
        "--host-sync", "--paths", str(bad), "--baseline", "none",
        "--json", "-", timeout=120,
    )
    assert res.returncode == 1, res.stdout + res.stderr
    doc = json.loads(res.stdout)
    assert any(f["rule"] == "sync-in-traced" for f in doc["findings"])
