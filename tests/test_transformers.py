"""BERT / GPT-2 / Llama+LoRA at tiny scale: shapes, training smoke runs,
and the LoRA param-partition (configs[2], [3], [4] of BASELINE.json).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from consensusml_tpu.consensus import GossipConfig
from consensusml_tpu.models.bert import BertConfig, BertMLM, bert_mlm_loss_fn
from consensusml_tpu.models.gpt2 import GPT2Config, GPT2LM, gpt2_loss_fn
from consensusml_tpu.models.llama import LlamaConfig, llama_tiny, llama_loss_fn
from consensusml_tpu.models.lora import lora_gossip_filter, lora_mask, lora_optimizer
from consensusml_tpu.topology import RingTopology, TorusTopology
from consensusml_tpu.train import (
    LocalSGDConfig,
    init_stacked_state,
    make_simulated_train_step,
)

VOCAB = 64


def _tiny_bert():
    return BertMLM(
        config=BertConfig(
            vocab_size=VOCAB, hidden=32, layers=2, heads=2, mlp_dim=64, max_len=32, dropout=0.0
        )
    )


def _tiny_gpt2():
    return GPT2LM(
        config=GPT2Config(
            vocab_size=VOCAB, hidden=32, layers=2, heads=2, max_len=32, dropout=0.0
        )
    )


def _lm_batches(world, h, batch, seq, rounds, seed=0, mlm=False):
    """Synthetic 'language': next token = (token + 1) % VOCAB — learnable."""
    rng = np.random.default_rng(seed)
    for _ in range(rounds):
        start = rng.integers(0, VOCAB, size=(world, h, batch, 1))
        ids = (start + np.arange(seq)) % VOCAB
        out = {"input_ids": jnp.asarray(ids, jnp.int32)}
        if mlm:
            mask = rng.random((world, h, batch, seq)) < 0.15
            corrupted = np.where(mask, VOCAB - 1, ids)
            out = {
                "input_ids": jnp.asarray(corrupted, jnp.int32),
                "labels": jnp.asarray(ids, jnp.int32),
                "mlm_mask": jnp.asarray(mask, jnp.float32),
            }
        yield out


def test_bert_shapes():
    model = _tiny_bert()
    ids = jnp.zeros((2, 16), jnp.int32)
    variables = model.init(jax.random.key(0), ids)
    logits = model.apply(variables, ids)
    assert logits.shape == (2, 16, VOCAB) and logits.dtype == jnp.float32


def test_gpt2_causality():
    """Changing a future token must not change past logits."""
    model = _tiny_gpt2()
    ids = jnp.zeros((1, 16), jnp.int32)
    variables = model.init(jax.random.key(0), ids)
    a = model.apply(variables, ids)
    b = model.apply(variables, ids.at[0, 10].set(5))
    np.testing.assert_allclose(a[0, :10], b[0, :10], atol=1e-5)
    assert not np.allclose(a[0, 10:], b[0, 10:], atol=1e-5)


def test_llama_forward_and_gqa():
    model = llama_tiny()  # kv_heads=2 < heads=4: exercises GQA
    ids = jnp.zeros((2, 16), jnp.int32)
    variables = model.init(jax.random.key(0), ids)
    logits = model.apply(variables, ids)
    assert logits.shape == (2, 16, 256)
    # causality with RoPE
    a = model.apply(variables, ids)
    b = model.apply(variables, ids.at[0, 12].set(9))
    np.testing.assert_allclose(a[0, :12], b[0, :12], atol=1e-4)


def test_config3_bert_local_sgd_h8():
    """BASELINE configs[2] at tiny scale: BERT MLM, local-SGD H=8 ring."""
    topo = RingTopology(4)
    model = _tiny_bert()
    cfg = LocalSGDConfig(
        gossip=GossipConfig(topology=topo), optimizer=optax.adam(1e-2), h=8
    )
    step = make_simulated_train_step(cfg, bert_mlm_loss_fn(model))
    init = lambda r: model.init(r, jnp.zeros((1, 16), jnp.int32))["params"]
    state = init_stacked_state(cfg, init, jax.random.key(0), 4)
    losses = []
    for batch in _lm_batches(4, h=8, batch=8, seq=16, rounds=40, mlm=True):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < 0.75 * losses[0], f"{losses[0]} -> {losses[-1]}"


def test_config5_gpt2_compressed_gossip():
    """BASELINE configs[4] at tiny scale: GPT-2 with topk+int8 gossip."""
    from consensusml_tpu.compress import topk_int8_compressor

    topo = RingTopology(4)
    model = _tiny_gpt2()
    cfg = LocalSGDConfig(
        gossip=GossipConfig(
            topology=topo,
            compressor=topk_int8_compressor(ratio=0.1, chunk=128),
            gamma=0.5,
        ),
        optimizer=optax.adam(3e-3),
        h=2,
    )
    step = make_simulated_train_step(cfg, gpt2_loss_fn(model))
    init = lambda r: model.init(r, jnp.zeros((1, 16), jnp.int32))["params"]
    state = init_stacked_state(cfg, init, jax.random.key(1), 4)
    losses = []
    for batch in _lm_batches(4, h=2, batch=8, seq=16, rounds=20, seed=3):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < 0.7 * losses[0], f"{losses[0]} -> {losses[-1]}"


def test_config4_llama_lora_torus():
    """BASELINE configs[3] at tiny scale: Llama + LoRA, torus gossip,
    adapters-only optimization and gossip; base weights stay frozen AND
    identical across workers."""
    topo = TorusTopology(2, 2)
    model = llama_tiny(lora_rank=4)
    cfg = LocalSGDConfig(
        gossip=GossipConfig(topology=topo, path_filter=lora_gossip_filter),
        optimizer=lora_optimizer(optax.adam(1e-2)),
        h=1,
    )
    step = make_simulated_train_step(cfg, llama_loss_fn(model))

    base_rng = jax.random.key(42)  # SHARED pretrained base across workers

    def init(rng):
        params = model.init(base_rng, jnp.zeros((1, 16), jnp.int32))["params"]
        # re-init adapters per worker so replicas disagree only in LoRA
        mask = lora_mask(params)
        leaves = jax.tree.leaves(params)
        keys = jax.random.split(rng, len(leaves))
        return jax.tree.unflatten(
            jax.tree.structure(params),
            [
                jax.random.normal(k, p.shape, p.dtype) * 0.05 if m else p
                for p, m, k in zip(
                    leaves, jax.tree.leaves(mask), keys
                )
            ],
        )

    state = init_stacked_state(cfg, init, jax.random.key(0), 4)
    base_before = {
        "k": np.asarray(
            state.params["layer_0"]["q_proj"]["base"]["kernel"], np.float32
        )
    }
    losses = []
    for batch in _lm_batches(4, h=1, batch=8, seq=16, rounds=10, seed=5):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]
    base_after = np.asarray(
        state.params["layer_0"]["q_proj"]["base"]["kernel"], np.float32
    )
    # frozen base: unchanged by optimizer AND untouched by gossip
    np.testing.assert_allclose(base_after, base_before["k"], atol=1e-7)
    # all workers share the same base
    assert np.allclose(base_after[0], base_after[1])
    # adapters DID move
    a0 = np.asarray(state.params["layer_0"]["q_proj"]["lora_a"])
    assert a0.std() > 0


def test_lora_mask_selects_adapters_only():
    model = llama_tiny(lora_rank=2)
    params = model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    mask = lora_mask(params)
    flat = jax.tree_util.tree_leaves_with_path(mask)
    lora_leaves = [v for p, v in flat if v]
    non_lora = [v for p, v in flat if not v]
    assert lora_leaves and non_lora
    n_lora = sum(
        1
        for p, v in jax.tree_util.tree_leaves_with_path(params)
        if any(getattr(k, "key", None) in ("lora_a", "lora_b") for k in p)
    )
    assert len(lora_leaves) == n_lora
