"""Push-sum (ratio consensus) properties:

1. On symmetric topologies without faults it equals plain gossip exactly
   (the mass stays 1).
2. Column stochasticity: the masked operator conserves total mass for ANY
   alive pattern on ANY (directed) topology, so all workers converge to
   the exact initial network mean — the property receive-side masked
   mixing provably lacks on directed graphs.
3. Collective (ppermute) and simulated (matrix) backends agree.
4. End-to-end: local-SGD with faults on a DIRECTED topology (rejected for
   plain gossip) trains under push_sum=True.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tests.conftest import compat_shard_map

from consensusml_tpu.comm import WorkerMesh, simulated
from consensusml_tpu.consensus import (
    ConsensusEngine,
    FaultConfig,
    GossipConfig,
    PushSumState,
    pushsum_init,
    pushsum_matrix,
    pushsum_round_collective,
    pushsum_round_simulated,
)
from consensusml_tpu.topology import (
    OnePeerExponentialTopology,
    RingTopology,
    TorusTopology,
    topology_from_name,
)


def _directed_phase(n):
    """A single directed one-peer phase (doubly stochastic, asymmetric)."""
    topo = OnePeerExponentialTopology(n)
    phase = topo.phases[1]  # offset 2: asymmetric for n > 4
    assert not phase.symmetric
    return phase


# ---------------------------------------------------------------------------
# operator-level properties (simulated backend)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name", ["ring", "torus", "dense", "exp"])
def test_pushsum_equals_plain_gossip_when_symmetric(name):
    topo = topology_from_name(name, 8)
    w = simulated.mixing_matrix(topo)
    rng = np.random.default_rng(0)
    x = {"a": jnp.asarray(rng.normal(size=(8, 3, 4)), jnp.float32)}
    state = pushsum_init(8)
    z, new_state = pushsum_round_simulated(x, state, w)
    want = simulated.mix_tree_stacked(x, w)
    np.testing.assert_allclose(np.asarray(z["a"]), np.asarray(want["a"]), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(new_state.w), 1.0, rtol=1e-6)


def test_pushsum_matrix_column_stochastic_any_alive_pattern():
    phase = _directed_phase(8)
    w = simulated.mixing_matrix(phase)
    rng = np.random.default_rng(1)
    for _ in range(20):
        alive = jnp.asarray(rng.integers(0, 2, size=8), jnp.float32)
        c = np.asarray(pushsum_matrix(w, alive))
        np.testing.assert_allclose(c.sum(axis=0), 1.0, atol=1e-6)
        assert (c >= -1e-12).all()
        # dead workers keep exactly their own value
        for i in np.where(np.asarray(alive) == 0)[0]:
            want = np.zeros(8)
            want[i] = 1.0
            np.testing.assert_allclose(c[i], want, atol=1e-12)


def test_pushsum_reaches_exact_mean_on_directed_graph_with_faults():
    """Masked push-sum converges to the TRUE initial mean; receive-side
    masked mixing on the same directed sequence drifts away from it."""
    n = 8
    topo = OnePeerExponentialTopology(n)
    # one phase alone (offset 2) is a disconnected graph; the full periodic
    # schedule is connected, so rotate through it like the trainer does
    ws = [simulated.mixing_matrix(p) for p in topo.phases]
    rng = np.random.default_rng(2)
    x0 = jnp.asarray(rng.normal(size=(n, 5)), jnp.float32)
    mean0 = np.asarray(x0).mean(axis=0)

    x, state = {"p": x0}, pushsum_init(n)
    for t in range(300):
        alive = jnp.asarray(rng.integers(0, 2, size=n) | (rng.random(n) < 0.5), jnp.float32)
        # ensure not everyone is dead
        alive = alive.at[t % n].set(1.0)
        x, state = pushsum_round_simulated(x, state, ws[t % len(ws)], alive)
    got = np.asarray(x["p"])
    np.testing.assert_allclose(got, np.broadcast_to(mean0, got.shape), atol=1e-4)


def test_receive_side_masking_biases_mean_on_directed_graph():
    """The counterexample motivating push-sum (documents the engine's
    restriction): receive-side masking on a directed graph moves the mean."""
    from consensusml_tpu.consensus import masked_mixing_matrix

    n = 8
    phase = _directed_phase(n)
    w = simulated.mixing_matrix(phase)
    alive = jnp.asarray([1, 1, 0, 1, 1, 1, 1, 1], jnp.float32)
    wp = np.asarray(masked_mixing_matrix(w, alive))
    # rows sum to 1 (no blow-up) but columns do NOT (mean shifts)
    np.testing.assert_allclose(wp.sum(axis=1), 1.0, atol=1e-6)
    assert not np.allclose(wp.sum(axis=0), 1.0, atol=1e-6)


# ---------------------------------------------------------------------------
# asymmetric, TIME-VARYING alive masks: drop mid-sequence, rejoin later
# ---------------------------------------------------------------------------


def _mask_sequence(n, rounds):
    """Deterministic churn-shaped mask sequence: worker 2 drops at round 3
    and rejoins two rounds later; worker 5 drops at round 6 and rejoins at
    round 8; everyone else stays up."""
    masks = []
    for t in range(rounds):
        a = np.ones(n, np.float32)
        if 3 <= t < 5:
            a[2] = 0.0
        if 6 <= t < 8 and n > 5:
            a[5] = 0.0
        masks.append(jnp.asarray(a))
    return masks


def test_pushsum_mass_conserved_under_time_varying_asymmetric_masks():
    """Mass conservation + weight convexity, round by round, while the
    alive mask CHANGES between rounds of a directed time-varying
    schedule (the swarm drop→rejoin scenario)."""
    n, rounds = 8, 10
    topo = OnePeerExponentialTopology(n)
    ws = [simulated.mixing_matrix(p) for p in topo.phases]
    rng = np.random.default_rng(4)
    x = {"p": jnp.asarray(rng.normal(size=(n, 6)), jnp.float32)}
    state = pushsum_init(n)
    mass_sum0 = float(np.sum(np.asarray(state.w)))
    num_sum0 = np.asarray(x["p"]).astype(np.float64).sum(axis=0)
    for t, alive in enumerate(_mask_sequence(n, rounds)):
        w_mat = ws[t % len(ws)]
        # weight CONVEXITY of the masked operator every round: columns
        # sum to 1 and every entry stays in [0, 1]
        c = np.asarray(pushsum_matrix(w_mat, alive))
        np.testing.assert_allclose(c.sum(axis=0), 1.0, atol=1e-6)
        assert (c >= -1e-12).all() and (c <= 1.0 + 1e-12).all()
        x, state = pushsum_round_simulated(x, state, w_mat, alive)
        # total mass and total (re-biased) numerator are conserved under
        # EVERY mask, including the rounds where membership just changed
        w_now = np.asarray(state.w, np.float64)
        np.testing.assert_allclose(w_now.sum(), mass_sum0, rtol=1e-5)
        num_now = (
            np.asarray(x["p"], np.float64) * w_now[:, None]
        ).sum(axis=0)
        np.testing.assert_allclose(num_now, num_sum0, rtol=1e-4, atol=1e-4)
    # and the de-biased estimates still head for the TRUE initial mean
    mean0 = num_sum0 / n
    for _ in range(120):
        for w_mat in ws:
            x, state = pushsum_round_simulated(x, state, w_mat)
    np.testing.assert_allclose(
        np.asarray(x["p"]), np.broadcast_to(mean0, (n, 6)), atol=1e-3
    )


def test_pushsum_round_collective_time_varying_asymmetric_masks():
    """pushsum_round_collective under the SAME drop-mid-sequence/
    rejoin-two-rounds-later mask sequence: per-round mass conservation,
    cross-backend agreement with the matrix operator, and weight
    positivity for every alive worker."""
    import functools

    from jax.sharding import PartitionSpec as P

    n, rounds = 8, 10
    topo = OnePeerExponentialTopology(n)
    phases = list(topo.phases)
    ws = [simulated.mixing_matrix(p) for p in phases]
    wmesh = WorkerMesh.create(
        phases[0], devices=jax.devices("cpu")[:n]
    )
    worker = P(*phases[0].axis_names)
    shard_map = compat_shard_map()

    def one_round(phase):
        @jax.jit
        @functools.partial(
            shard_map,
            mesh=wmesh.mesh,
            in_specs=(worker, worker, worker),
            out_specs=(worker, worker),
        )
        def f(x, w, alive):
            sq = lambda v: v.reshape(v.shape[1:])
            z, st = pushsum_round_collective(
                {"p": sq(x)}, PushSumState(w=sq(w)), phase, sq(alive)
            )
            un = lambda v: v.reshape((1,) + v.shape)
            return un(z["p"]), un(st.w)

        return f

    steps = [one_round(p) for p in phases]
    rng = np.random.default_rng(5)
    x0 = jnp.asarray(rng.normal(size=(n, 6)), jnp.float32)
    x_col, w_col = x0, jnp.ones((n,), jnp.float32)
    x_sim, st_sim = {"p": x0}, pushsum_init(n)
    for t, alive in enumerate(_mask_sequence(n, rounds)):
        x_col, w_col = steps[t % len(phases)](x_col, w_col, alive)
        x_sim, st_sim = pushsum_round_simulated(
            x_sim, st_sim, ws[t % len(ws)], alive
        )
        w_host = np.asarray(w_col, np.float64)
        # mass conserved every round of the asymmetric masked sequence
        np.testing.assert_allclose(w_host.sum(), float(n), rtol=1e-5)
        # weights stay a convex combination: non-negative everywhere,
        # strictly positive for alive workers
        assert (w_host >= -1e-6).all()
        assert (w_host[np.asarray(alive) > 0] > 0).all()
        # the two backends run the identical operator
        np.testing.assert_allclose(
            np.asarray(x_col), np.asarray(x_sim["p"]), rtol=1e-4, atol=1e-5
        )
        np.testing.assert_allclose(
            w_host, np.asarray(st_sim.w), rtol=1e-5, atol=1e-6
        )


# ---------------------------------------------------------------------------
# collective backend agreement
# ---------------------------------------------------------------------------


def _collective_round(topo, x_stacked, w_stacked, alive_stacked):
    wmesh = WorkerMesh.create(topo, devices=jax.devices("cpu")[: topo.world_size])
    from jax.sharding import PartitionSpec as P
    import functools

    worker = P(*topo.axis_names)
    n_axes = len(topo.mesh_shape)

    @jax.jit
    @functools.partial(
        compat_shard_map(),
        mesh=wmesh.mesh,
        in_specs=(worker, worker, worker),
        out_specs=(worker, worker),
    )
    def f(x, w, alive):
        sq = lambda t: jax.tree.map(lambda v: v.reshape(v.shape[n_axes:]), t)
        x, w, alive = sq(x), sq(w), sq(alive)
        z, st = pushsum_round_collective(
            {"p": x}, pushsum_init().__class__(w=w), topo, alive
        )
        un = lambda t: jax.tree.map(lambda v: v.reshape((1,) * n_axes + v.shape), t)
        return un(z["p"]), un(st.w)

    to_mesh = lambda v: v.reshape(topo.mesh_shape + v.shape[1:])
    z, wn = f(to_mesh(x_stacked), to_mesh(w_stacked), to_mesh(alive_stacked))
    flat = lambda v: np.asarray(v).reshape((topo.world_size,) + v.shape[n_axes:])
    return flat(z), flat(wn)


@pytest.mark.parametrize(
    "topo",
    [RingTopology(8), TorusTopology(2, 4), topology_from_name("dense", 8),
     _directed_phase(8)],
    ids=["ring", "torus", "dense", "directed"],
)
def test_collective_matches_simulated(topo):
    n = topo.world_size
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(n, 6)), jnp.float32)
    w0 = jnp.asarray(rng.uniform(0.5, 1.5, size=n), jnp.float32)
    alive = jnp.asarray([1, 0, 1, 1, 1, 0, 1, 1], jnp.float32)

    wmat = simulated.mixing_matrix(topo)
    z_sim, st_sim = pushsum_round_simulated(
        {"p": x}, pushsum_init(n).__class__(w=w0), wmat, alive
    )
    z_col, w_col = _collective_round(topo, x, w0, alive)
    np.testing.assert_allclose(z_col, np.asarray(z_sim["p"]), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(w_col, np.asarray(st_sim.w), rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# engine + trainer integration
# ---------------------------------------------------------------------------


def test_engine_rejects_directed_faults_without_pushsum_and_accepts_with():
    topo = OnePeerExponentialTopology(8)
    with pytest.raises(NotImplementedError, match="push_sum"):
        GossipConfig(topology=topo, faults=FaultConfig(drop_prob=0.2))
    GossipConfig(topology=topo, faults=FaultConfig(drop_prob=0.2), push_sum=True)


def test_local_sgd_trains_with_pushsum_faults_on_directed_topology():
    from consensusml_tpu.data import SyntheticClassification, round_batches
    from consensusml_tpu.models import MLP, mlp_loss_fn
    from consensusml_tpu.train import (
        LocalSGDConfig,
        init_stacked_state,
        make_simulated_train_step,
    )

    n = 8
    topo = OnePeerExponentialTopology(n)
    model = MLP(hidden=16)
    cfg = LocalSGDConfig(
        gossip=GossipConfig(
            topology=topo, faults=FaultConfig(drop_prob=0.25), push_sum=True
        ),
        optimizer=optax.sgd(0.1),
        h=2,
    )
    step = make_simulated_train_step(cfg, mlp_loss_fn(model))
    state = init_stacked_state(
        cfg,
        lambda r: model.init(r, jnp.zeros((1, 8, 8, 1)))["params"],
        jax.random.key(0),
        n,
    )
    data = SyntheticClassification(n=512, image_shape=(8, 8, 1))
    losses = []
    for batch in round_batches(data, n, h=2, batch=16, rounds=30, seed=0):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
        assert np.isfinite(losses[-1])
    assert np.mean(losses[-5:]) < np.mean(losses[:5])
    # push-sum mass stays positive and near 1 on average
    w = np.asarray(state.gossip.w)
    assert (w > 0).all()
    np.testing.assert_allclose(w.mean(), 1.0, atol=1e-3)
