"""Cluster observability plane: labeled metrics, per-link probes,
consensus-health monitor, cross-rank aggregation, and the train.py
surface (docs/observability.md "Cluster view").

Acceptance anchors (ISSUE 6): a deliberately slowed link must rank
slowest in the report, a deliberately diverged replica must trip the
health anomaly, and a multi-rank directory must merge into one
deterministic cluster report — all asserted here, tier-1 fast.
"""

import importlib.util
import json
import math
import os
import time

import jax
import jax.numpy as jnp
import pytest

from consensusml_tpu.comm import simulated
from consensusml_tpu.consensus import ConsensusEngine, GossipConfig
from consensusml_tpu.obs import (
    ClusterWriter,
    ConsensusHealthMonitor,
    LinkProber,
    MetricsRegistry,
    SpanTracer,
    aggregate,
    decay_bound,
    link_wire_bytes,
    parse_metric_key,
)
from consensusml_tpu.obs.links import edge_sends_per_round
from consensusml_tpu.topology import (
    OnePeerExponentialTopology,
    RingTopology,
    TorusTopology,
)

pytestmark = pytest.mark.telemetry


def _tool(name):
    spec = importlib.util.spec_from_file_location(
        name,
        os.path.join(os.path.dirname(__file__), "..", "tools", f"{name}.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# labeled metrics
# ---------------------------------------------------------------------------


def test_labeled_metrics_exposition_and_snapshot_keys():
    r = MetricsRegistry()
    r.counter("t_edge_total", "bytes", labels={"src": 0, "dst": 1}).inc(5)
    r.counter("t_edge_total", labels={"src": 1, "dst": 0}).inc(7)
    h = r.histogram(
        "t_edge_seconds", buckets=(0.1, 1.0), labels={"src": 0, "dst": 1}
    )
    h.observe(0.5)
    text = r.to_prometheus()
    assert 't_edge_total{dst="1",src="0"} 5' in text
    assert 't_edge_total{dst="0",src="1"} 7' in text
    # one TYPE header per family, not per child
    assert text.count("# TYPE t_edge_total counter") == 1
    assert 't_edge_seconds_bucket{dst="1",le="0.1",src="0"} 0' in text
    snap = r.snapshot()["metrics"]
    assert snap['t_edge_total{dst="1",src="0"}'] == 5.0
    name, labels = parse_metric_key('t_edge_total{dst="1",src="0"}')
    assert name == "t_edge_total" and labels == {"dst": "1", "src": "0"}
    assert parse_metric_key("t_plain") == ("t_plain", {})
    # family kind is enforced across label children
    with pytest.raises(ValueError):
        r.gauge("t_edge_total", labels={"src": 9, "dst": 9})


# ---------------------------------------------------------------------------
# topology edge sets
# ---------------------------------------------------------------------------


def test_topology_edges_match_mixing_matrix():
    for topo in (RingTopology(5), TorusTopology(2, 3), RingTopology(2)):
        w = topo.mixing_matrix()
        edges = {(s, d): wt for s, d, wt in topo.edges()}
        for dst in range(topo.world_size):
            for src in range(topo.world_size):
                if src == dst:
                    continue
                if w[dst, src] > 0:
                    assert edges[(src, dst)] == pytest.approx(w[dst, src])
                else:
                    assert (src, dst) not in edges


def test_time_varying_edges_average_over_period():
    topo = OnePeerExponentialTopology(4)  # phases: offset 1, offset 2
    edges = {(s, d): wt for s, d, wt in topo.edges()}
    # each phase's single edge carries weight 0.5, active 1-in-2 rounds
    assert edges[(0, 1)] == pytest.approx(0.25)
    assert edges[(0, 2)] == pytest.approx(0.25)
    # a ring-of-2's +1/-1 shifts are SEPARATE sends on one edge
    assert edge_sends_per_round(RingTopology(2)) == {(0, 1): 2.0, (1, 0): 2.0}


# ---------------------------------------------------------------------------
# per-link probes
# ---------------------------------------------------------------------------


def test_slowed_link_is_ranked_slowest():
    topo = RingTopology(4)
    reg = MetricsRegistry()

    def transfer(src, dst):
        if (src, dst) == (2, 3):
            time.sleep(0.002)

    prober = LinkProber(topo, registry=reg, transfer=transfer)
    assert len(prober.edges) == 8 and prober.skipped_edges == 0
    for _ in range(3):
        prober.probe_round()
    top = prober.slowest(1)[0]
    assert (top["src"], top["dst"]) == (2, 3)
    assert top["probes"] == 3
    text = reg.to_prometheus()
    assert 'consensusml_link_latency_seconds_bucket{dst="3"' in text
    assert "consensusml_link_probe_rounds_total 3" in text
    assert 'consensusml_link_bandwidth_bytes_per_sec{dst="0",src="1"}' in text


def test_link_prober_max_edges_counted_not_silent():
    reg = MetricsRegistry()
    prober = LinkProber(RingTopology(6), registry=reg, max_edges=4,
                        transfer=lambda s, d: None)
    assert len(prober.edges) == 4 and prober.skipped_edges == 8
    assert reg.gauge("consensusml_link_edges_skipped").value == 8


def test_link_prober_default_transfer_times_device_copies():
    # real device_put probes over the virtual CPU mesh: values are
    # host-memcpy latencies, but every edge must land a measurement
    topo = RingTopology(4)
    reg = MetricsRegistry()
    prober = LinkProber(
        topo, registry=reg, devices=jax.devices()[:4], payload_bytes=1 << 12
    )
    lat = prober.probe_round()
    assert set(lat) == set(prober.edges)
    assert all(v > 0 for v in lat.values())


def test_link_wire_bytes_matches_engine_accounting():
    shapes = jax.eval_shape(
        lambda: {"w": jnp.zeros((256, 64), jnp.float32)}
    )
    for world in (2, 4):
        eng = ConsensusEngine(GossipConfig(topology=RingTopology(world)))
        per_edge = link_wire_bytes(eng, shapes)
        for rank in range(world):
            outgoing = sum(
                b for (s, _), b in per_edge.items() if s == rank
            )
            assert outgoing == pytest.approx(
                eng.wire_bytes_per_round(shapes)
            )


# ---------------------------------------------------------------------------
# consensus-health monitor
# ---------------------------------------------------------------------------


def test_health_strict_pure_gossip_stays_within_bound():
    topo = RingTopology(8)
    w = simulated.mixing_matrix(topo)
    x = jax.random.normal(jax.random.key(0), (8, 128))
    reg = MetricsRegistry()
    mon = ConsensusHealthMonitor(
        topo, registry=reg, tracer=SpanTracer(), strict=True
    )
    assert mon.bound == pytest.approx(1.0 - topo.spectral_gap())
    for rnd in range(12):
        d = float(simulated.consensus_error_stacked({"x": x}, 8))
        assert mon.observe(rnd, d) is None
        x = simulated.mix_stacked(x, w)
    # the spectral bound is worst-case: measured decay must respect it
    assert mon.measured_decay <= mon.bound + mon.tolerance
    assert reg.gauge("consensusml_health_bound_violation").value == 0.0
    assert reg.counter("consensusml_health_anomalies_total").value == 0


def test_deliberately_diverged_replica_trips_anomaly(capsys):
    topo = RingTopology(8)
    eng = ConsensusEngine(GossipConfig(topology=topo))
    w = simulated.mixing_matrix(topo)
    params = {"x": jax.random.normal(jax.random.key(1), (8, 64))}
    reg = MetricsRegistry()
    mon = ConsensusHealthMonitor(topo, registry=reg, tracer=SpanTracer())
    first = None
    for rnd in range(10):
        params, _ = eng.round_simulated(params, None, w)
        # replica 0 diverges harder every round (a poisoned update)
        params["x"] = params["x"].at[0].add(2.0 ** rnd)
        d = float(simulated.consensus_error_stacked(params, 8))
        rec = mon.observe(rnd, d)
        if rec and first is None:
            first = rec
    assert first is not None and first["kind"] == "divergence"
    assert first["streak"] == mon.sustain
    assert reg.gauge("consensusml_health_bound_violation").value == 1.0
    assert reg.counter("consensusml_health_anomalies_total").value == 1
    assert "consensus-health ANOMALY" in capsys.readouterr().err


def test_health_nonfinite_distance_is_divergence():
    mon = ConsensusHealthMonitor(
        RingTopology(4), registry=MetricsRegistry(), tracer=SpanTracer(),
        sustain=2,
    )
    assert mon.observe(0, 0.5) is None
    assert mon.observe(1, float("nan")) is None  # streak 1
    rec = mon.observe(2, float("nan"))  # streak 2 = sustain
    assert rec is not None and rec["kind"] == "divergence"


def test_decay_bound_time_varying_is_per_round_rate():
    topo = OnePeerExponentialTopology(8)
    per_period = 1.0 - topo.spectral_gap()
    assert decay_bound(topo) == pytest.approx(
        per_period ** (1.0 / topo.period)
    )


# ---------------------------------------------------------------------------
# cross-rank aggregation -> one cluster report
# ---------------------------------------------------------------------------


def _write_rank(tmp_path, rank, *, rounds, lat_s, heartbeat_ago=0.0,
                slow_edge=None, now=None):
    now = time.time() if now is None else now
    reg = MetricsRegistry()
    reg.counter("consensusml_rounds_total").inc(rounds)
    h = reg.histogram("consensusml_round_latency_seconds")
    for _ in range(rounds):
        h.observe(lat_s)
    reg.gauge("consensusml_consensus_distance").set(0.25)
    reg.gauge("consensusml_health_decay_measured").set(0.76)
    reg.gauge("consensusml_health_decay_bound").set(0.80)
    reg.gauge("consensusml_health_bound_violation").set(0.0)

    def transfer(src, dst):
        if slow_edge and (src, dst) == slow_edge:
            time.sleep(0.002)

    prober = LinkProber(RingTopology(4), registry=reg, transfer=transfer)
    prober.probe_round()
    writer = ClusterWriter(
        str(tmp_path), rank=rank, registry=reg, world_size=2
    )
    writer.write(round=rounds)
    if heartbeat_ago:
        doc = json.load(open(writer.path))
        doc["heartbeat_s"] = now - heartbeat_ago
        json.dump(doc, open(writer.path, "w"))
    return writer


def test_two_rank_directory_merges_into_one_report(tmp_path):
    now = time.time()
    _write_rank(tmp_path, 0, rounds=10, lat_s=0.1, slow_edge=(1, 2), now=now)
    _write_rank(
        tmp_path, 1, rounds=6, lat_s=0.3, heartbeat_ago=500.0, now=now
    )
    doc = aggregate(str(tmp_path), now=now)
    # per-rank skew
    assert doc["skew"]["ranks"] == 2
    assert doc["skew"]["round_lag"] == 4
    assert doc["skew"]["round_latency_skew"] == pytest.approx(3.0, rel=1e-6)
    # merged link histograms: both ranks probed each edge once, so every
    # edge shows 2 probes and the deliberately slowed one ranks first
    top = doc["links"][0]
    assert (top["src"], top["dst"]) == (1, 2)
    assert top["probes"] == 2
    # straggler: stale heartbeat AND 4 rounds behind
    (s,) = doc["stragglers"]
    assert s["rank"] == 1 and len(s["reasons"]) == 2
    # measured-vs-bound health made it through
    assert doc["health"]["decay_bound"] == 0.80
    assert doc["health"]["decay_measured_worst"] == 0.76
    assert doc["health"]["ranks_in_violation"] == 0
    # determinism: aggregating the same dir at the same instant is stable
    assert aggregate(str(tmp_path), now=now) == doc


def test_obs_report_tool_renders_text_and_json(tmp_path, capsys):
    now = time.time()
    _write_rank(tmp_path, 0, rounds=5, lat_s=0.1, slow_edge=(3, 0), now=now)
    mod = _tool("obs_report")
    rc = mod.main([str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "links (slowest first" in out
    rows = [
        l for l in out.splitlines() if "->" in l and "src->dst" not in l
    ]
    assert rows[0].strip().startswith("3->0")  # slow edge ranks first
    rc = mod.main([str(tmp_path), "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert doc["links"][0]["src"] == 3
    # missing dir: clear error, rc 1
    assert mod.main([str(tmp_path / "nope")]) == 1
    assert "does not exist" in capsys.readouterr().err


def test_obs_report_tool_empty_dir_errors(tmp_path, capsys):
    mod = _tool("obs_report")
    assert mod.main([str(tmp_path)]) == 1
    assert "no obs-" in capsys.readouterr().err


def test_partial_snapshots_render_with_absent_blocks(tmp_path, capsys):
    """The degraded-cluster fixture (ISSUE 15): rank files missing every
    optional section — no metrics, no serving traces, no links, no
    swarm events, no alert plane, even a null metrics map — must render
    a full report with those blocks marked absent, never crash."""
    # bare-minimum identity-only snapshot (a writer that died right
    # after its first write)
    (tmp_path / "obs-rank-00000.json").write_text(
        json.dumps({"rank": 0, "role": "rank", "heartbeat_s": time.time()})
    )
    # a snapshot with round progress but a NULL metrics map and no
    # heartbeat at all
    (tmp_path / "obs-rank-00001.json").write_text(
        json.dumps({"rank": 1, "role": "rank", "round": 3, "metrics": None})
    )
    doc = aggregate(str(tmp_path))
    assert doc["skew"]["ranks"] == 2
    assert doc["alerts"] is None and doc["history"] is None
    mod = _tool("obs_report")
    rc = mod.main([str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    for block in (
        "alerts: absent",
        "links: absent",
        "request traces: absent",
        "round timeline: absent",
        "membership: absent",
        "history: absent",
    ):
        assert block in out, f"missing absent marker: {block!r}\n{out}"
    # and a MIXED directory — one partial file next to one full rank —
    # still renders the full rank's sections
    _write_rank(tmp_path, 2, rounds=5, lat_s=0.1, slow_edge=(1, 0))
    rc = mod.main([str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "links (slowest first" in out
    assert "alerts: absent" in out  # still no alert plane anywhere


def test_flight_recorder_dumps_are_indexed(tmp_path):
    from consensusml_tpu.obs import FlightRecorder

    _write_rank(tmp_path, 0, rounds=3, lat_s=0.1)
    rec = FlightRecorder(
        str(tmp_path), tracer=SpanTracer(), registry=MetricsRegistry()
    )
    rec.dump("unit-test")
    doc = aggregate(str(tmp_path))
    (fr,) = doc["flight_recorders"]
    assert fr["file"].startswith("flightrec-") and fr["bytes"] > 0


# ---------------------------------------------------------------------------
# loadgen client-side SLO snapshots merge into the same report
# ---------------------------------------------------------------------------


def test_loadgen_metrics_merge_with_rank_snapshots(tmp_path):
    lg = _tool("loadgen")
    from consensusml_tpu.obs import MetricsHistory, get_registry

    def submit(ids, max_new, ctx, sampling=None):
        time.sleep(0.02)  # give the history sampler ticks to land on
        return {"ttft_s": 0.01, "latency_s": 0.05, "tokens": [1] * max_new}

    reg = get_registry()
    history = MetricsHistory(reg, keep=64)
    report = lg.run_loadgen(
        submit, n_requests=4, rate_rps=200.0, prompt_lens=(4, 8),
        vocab=64, max_new_tokens=2,
        history=history, history_tick_s=0.01,
    )
    assert report["completed"] == 4
    assert reg.histogram("consensusml_loadgen_ttft_seconds").count >= 4
    # the sampler thread recorded the client rings DURING the run
    assert "consensusml_loadgen_ttft_seconds" in history.keys()
    assert len(history.last("consensusml_loadgen_ttft_seconds", 1000)) >= 2
    ClusterWriter(
        str(tmp_path), rank=0, role="loadgen", registry=reg,
        history=history,
    ).write(extra={"report": report})
    _write_rank(tmp_path, 0, rounds=3, lat_s=0.1)
    doc = aggregate(str(tmp_path))
    (client,) = doc["clients"]
    assert client["role"] == "loadgen"
    ttft = client["metrics"]["consensusml_loadgen_ttft_seconds"]
    assert ttft["count"] >= 4 and math.isfinite(ttft["p99"])
    # the rank rows are unaffected by the client snapshot
    assert len(doc["ranks"]) == 1
    # and the client-side history digest rides the merge: the TTFT
    # sparkline row the report joins against the server side
    assert doc["history"] is not None
    series = {r["series"] for r in doc["history"]["series"]}
    assert "consensusml_loadgen_ttft_seconds" in series


# ---------------------------------------------------------------------------
# round timeline + slowest-request table: two ranks + a loadgen client
# ---------------------------------------------------------------------------


def _write_rank_with_digest(
    tmp_path, rank, *, rounds, lat_s, feed_s, now
):
    """A rank snapshot whose span digest carries per-round phase rows
    (train.round + round.feed/round.fence) and a compile-phase ratio
    (gossip.round vs train.inner_loop at 3:1)."""
    reg = MetricsRegistry()
    reg.counter("consensusml_rounds_total").inc(rounds)
    tracer = SpanTracer()
    tracer.complete("gossip.round", 0.03)
    tracer.complete("train.inner_loop", 0.01)
    for r in range(rounds):
        tracer.complete("round.feed", feed_s, round=r)
        tracer.complete("round.fence", lat_s / 2, round=r)
        tracer.complete("train.round", lat_s, round=r)
    ClusterWriter(
        str(tmp_path), rank=rank, registry=reg, world_size=2, tracer=tracer
    ).write(round=rounds - 1)


def test_round_timeline_and_request_table_merge_deterministically(tmp_path):
    """The ISSUE-10 cluster fixture: two ranks with span digests (rank 1
    is the straggler, its extra time dominated by feed stall) plus a
    loadgen client snapshot carrying exemplar-bearing SLOs and the
    request-trace dump — one deterministic merged report with the
    cross-rank round timeline and the slowest-request table."""
    from consensusml_tpu.obs import RequestTraceRegistry, TraceContext
    from consensusml_tpu.obs.metrics import DEFAULT_SLO_BUCKETS

    now = time.time()
    _write_rank_with_digest(
        tmp_path, 0, rounds=3, lat_s=0.10, feed_s=0.001, now=now
    )
    _write_rank_with_digest(
        tmp_path, 1, rounds=3, lat_s=0.30, feed_s=0.180, now=now
    )

    # loadgen client: two traced requests, the slow one exemplared
    reg = MetricsRegistry()
    rt = RequestTraceRegistry()
    for rid, ttft in (("lgf-00000", 0.004), ("lgf-00001", 0.212)):
        ctx = TraceContext(rid)
        rt.start(ctx, 4)
        rt.event(ctx.request_id, "admission", slot=0, bucket=8)
        rt.event(ctx.request_id, "prefill", bucket=8)
        rt.decode_tick(ctx.request_id)
        rt.finish(ctx.request_id, "max_tokens", tokens=3)
        reg.histogram(
            "consensusml_loadgen_ttft_seconds", buckets=DEFAULT_SLO_BUCKETS
        ).observe(ttft, exemplar=ctx.request_id)
    ClusterWriter(
        str(tmp_path), rank=0, role="loadgen", registry=reg
    ).write(extra={"request_traces": rt.snapshot()})

    doc = aggregate(str(tmp_path), now=now)

    # ---- round timeline: 3 rounds, rank 1 the feed-bound straggler ------
    timeline = doc["round_timeline"]
    assert [row["round"] for row in timeline] == [0, 1, 2]
    for row in timeline:
        assert [r["rank"] for r in row["ranks"]] == [0, 1]
        st = row["straggler"]
        assert st["rank"] == 1
        assert st["extra_ms"] == pytest.approx(200.0, abs=1.0)
        assert st["phase"] == "feed"
        assert st["feed_ms"] == pytest.approx(179.0, abs=1.0)
        # the non-feed remainder splits 3:1 gossip:compute (the digest's
        # compile-round ratio), marked as an estimate
        assert st["gossip_ms_est"] == pytest.approx(
            0.75 * (st["extra_ms"] - st["feed_ms"]), rel=1e-6
        )

    # ---- slowest-request table: exemplar resolves to the trace ----------
    req = doc["requests"]
    assert req["traces_indexed"] == 2 and req["in_flight"] == 0
    (top, second) = req["slowest"]
    assert top["metric"] == "consensusml_loadgen_ttft_seconds"
    assert top["side"] == "client"
    assert top["request_id"] == "lgf-00001/0"
    assert top["resolved"] and top["trace_id"] == "lgf-00001"
    assert top["trace"]["decode_ticks"] == 1
    assert "prefill" in top["trace"]["events"]
    assert second["request_id"] == "lgf-00000/0"

    # ---- deterministic merge + rendered report --------------------------
    assert aggregate(str(tmp_path), now=now) == doc
    mod = _tool("obs_report")
    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert mod.main([str(tmp_path)]) == 0
    out = buf.getvalue()
    assert "slowest requests (SLO exemplars -> traces):" in out
    assert "lgf-00001/0" in out
    assert "round timeline (cross-rank, straggler time by phase):" in out
    assert "-> feed" in out


# ---------------------------------------------------------------------------
# the 3-round simulated-comm smoke: train.py with the cluster plane on
# ---------------------------------------------------------------------------


def test_train_smoke_link_probes_and_cluster_report(tmp_path):
    import train as train_cli
    from consensusml_tpu.obs import get_tracer

    obs_dir = tmp_path / "obs"
    prom = tmp_path / "m.prom"
    tracer = get_tracer()
    was_enabled = tracer.enabled
    try:
        rc = train_cli.main(
            [
                "--config", "mnist_mlp",
                "--device", "cpu",
                "--backend", "simulated",
                "--rounds", "3",
                "--telemetry-every", "2",
                "--link-probes",
                "--obs-cluster-dir", str(obs_dir),
                "--metrics-prom", str(prom),
            ]
        )
    finally:
        tracer.enabled = was_enabled
        tracer.clear()  # the GLOBAL ring: later trace tests count spans
    assert rc == 0

    # prometheus carries the link + health families
    text = open(prom).read()
    assert "# TYPE consensusml_link_latency_seconds histogram" in text
    assert "consensusml_link_wire_bytes_per_round{" in text
    assert "# TYPE consensusml_health_decay_bound gauge" in text
    assert "consensusml_round_progress 2" in text

    # the rank snapshot aggregates into a cluster report
    doc = aggregate(str(obs_dir))
    assert [r["rank"] for r in doc["ranks"]] == [0]
    row = doc["ranks"][0]
    assert row["round"] == 2
    # >=: the process-wide registry accumulates across in-process runs
    assert row["round_latency"]["count"] >= 3
    assert row["health"]["decay_bound"] is not None
    probed = [l for l in doc["links"] if l["probes"] > 0]
    assert probed, "link probes produced no per-edge histograms"
    assert all(l["wire_bytes_per_round"] for l in probed)
    assert doc["stragglers"] == []


# ---------------------------------------------------------------------------
# fleet section (ISSUE 20): router snapshots merge + render
# ---------------------------------------------------------------------------


def test_fleet_snapshots_merge_and_render(tmp_path, capsys):
    """Two routers writing ``fleet`` snapshot extras (fleetctl
    --obs-snapshot) merge into one cluster-report section: stream
    counters SUM across routers, the replica table and canary state
    merge by name / last-writer, and obs_report renders the fleet rows
    (docs/fleet.md "Observability")."""
    def fleet_doc(accepted, replicas, canary=None, events=()):
        return {
            "router": {
                "policy": "score",
                "accepted": accepted,
                "completed": accepted - 1,
                "rejected": 1,
                "client_gone": 0,
                "lost_streams": 0,
                "redispatches": 2,
                "affinity_hits": 3,
            },
            "replicas": replicas,
            "canary": canary,
            "events": list(events),
        }

    rep0 = {"r0": {"ready": True, "queue_depth": 1, "generation": 2,
                   "hbm_free_bytes": 1 << 20, "firing": []}}
    rep1 = {"r1": {"ready": False, "queue_depth": None, "generation": None,
                   "hbm_free_bytes": None, "firing": ["serve-queue-full"]}}
    ClusterWriter(str(tmp_path), rank=0, role="router").write(
        extra={"fleet": fleet_doc(
            10, rep0,
            events=[{"time_s": 2.0, "kind": "canary-promote",
                     "replicas": ["r1"]}],
        )}
    )
    ClusterWriter(str(tmp_path), rank=1, role="router").write(
        extra={"fleet": fleet_doc(
            4, rep1,
            canary={"state": "promoted", "replica": "r0",
                    "target_generation": 2},
            events=[{"time_s": 1.0, "kind": "canary-start",
                     "replica": "r0"}],
        )}
    )
    # a third, fleet-less rank must not disturb the section
    ClusterWriter(str(tmp_path), rank=2, role="train").write(round=1)

    doc = aggregate(str(tmp_path))
    fl = doc["fleet"]
    assert fl["routers_reporting"] == 2
    assert fl["router"]["accepted"] == 14  # summed across routers
    assert fl["router"]["completed"] == 12
    assert fl["router"]["rejected"] == 2
    assert fl["router"]["policy"] == "score"  # non-numeric: first wins
    assert set(fl["replicas"]) == {"r0", "r1"}
    assert fl["canary"]["state"] == "promoted"
    assert [e["kind"] for e in fl["events"]] == [
        "canary-start", "canary-promote",  # time-sorted across ranks
    ]

    mod = _tool("obs_report")
    assert mod.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "fleet (2 router(s), policy=score)" in out
    assert "accepted=14" in out and "lost=0" in out
    assert "canary: state=promoted replica=r0 target_gen=2" in out
    assert "event: canary-start" in out

    # a directory with no fleet snapshots carries no fleet section
    bare = tmp_path / "bare"
    bare.mkdir()
    ClusterWriter(str(bare), rank=0).write(round=1)
    assert aggregate(str(bare)).get("fleet") is None
    assert mod.main([str(bare)]) == 0
    assert "fleet (" not in capsys.readouterr().out
