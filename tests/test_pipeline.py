"""Pipeline parallelism must match the sequential layer stack exactly —
forward AND backward (autodiff through the collective schedule) — and
train end-to-end."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from consensusml_tpu.parallel import pipeline_apply, pipeline_last_stage_mean


def _mesh(p):
    return Mesh(np.array(jax.devices("cpu")[:p]), ("pp",))


def _layer(w, x):
    return jnp.tanh(x @ w)


def _stage_fn(stage_params, x):
    # apply this stage's local slice of the layer stack in order
    def body(h, w):
        return _layer(w, h), None

    y, _ = jax.lax.scan(body, x, stage_params)
    return y


def _sequential(all_w, mb):
    def per_mb(x):
        def body(h, w):
            return _layer(w, h), None

        y, _ = jax.lax.scan(body, x, all_w)
        return y

    return jax.vmap(per_mb)(mb)


def _run_pipeline(all_w, mb, p):
    mesh = _mesh(p)

    @jax.jit
    @functools.partial(
        jax.shard_map, mesh=mesh, in_specs=(P("pp"), P()), out_specs=P()
    )
    def f(w, mb):
        outs = pipeline_apply(_stage_fn, w, mb, "pp")
        # replicate the last stage's outputs for comparison
        return pipeline_last_stage_mean(outs, "pp")

    w_sharded = jax.device_put(all_w, NamedSharding(mesh, P("pp")))
    return np.asarray(f(w_sharded, mb))


@pytest.mark.parametrize("p,m", [(2, 4), (4, 8), (8, 8)])
def test_pipeline_matches_sequential_forward(p, m):
    rng = np.random.default_rng(0)
    layers, b, d = 8, 4, 16
    all_w = jnp.asarray(rng.normal(size=(layers, d, d)) * 0.5, jnp.float32)
    mb = jnp.asarray(rng.normal(size=(m, b, d)), jnp.float32)
    want = np.asarray(_sequential(all_w, mb))
    got = _run_pipeline(all_w, mb, p)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_pipeline_gradients_match_sequential():
    rng = np.random.default_rng(1)
    layers, m, b, d, p = 8, 8, 2, 8, 4
    all_w = jnp.asarray(rng.normal(size=(layers, d, d)) * 0.5, jnp.float32)
    mb = jnp.asarray(rng.normal(size=(m, b, d)), jnp.float32)
    tgt = jnp.asarray(rng.normal(size=(m, b, d)), jnp.float32)
    mesh = _mesh(p)

    def seq_loss(w):
        return jnp.mean((_sequential(w, mb) - tgt) ** 2)

    @jax.jit
    @functools.partial(
        jax.shard_map, mesh=mesh, in_specs=P("pp"), out_specs=P("pp")
    )
    def pp_grad(w):
        def loss(w):
            outs = pipeline_apply(_stage_fn, w, mb, "pp")
            per = jnp.mean((outs - tgt) ** 2)
            return pipeline_last_stage_mean(per, "pp")

        return jax.grad(loss)(w)

    w_sharded = jax.device_put(all_w, NamedSharding(mesh, P("pp")))
    got = np.asarray(jax.device_get(pp_grad(w_sharded)))
    want = np.asarray(jax.grad(seq_loss)(all_w))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-6)


def test_pipeline_trains():
    """A pipelined deep tanh stack fits a random mapping (loss decreases)."""
    rng = np.random.default_rng(2)
    layers, m, b, d, p = 4, 8, 4, 8, 4
    w = jnp.asarray(rng.normal(size=(layers, d, d)) * 0.3, jnp.float32)
    mb = jnp.asarray(rng.normal(size=(m, b, d)), jnp.float32)
    tgt = jnp.tanh(jnp.asarray(rng.normal(size=(m, b, d)), jnp.float32))
    mesh = _mesh(p)

    @jax.jit
    @functools.partial(
        jax.shard_map, mesh=mesh, in_specs=P("pp"), out_specs=(P("pp"), P())
    )
    def train_step(w):
        def loss(w):
            outs = pipeline_apply(_stage_fn, w, mb, "pp")
            return pipeline_last_stage_mean(jnp.mean((outs - tgt) ** 2), "pp")

        l, g = jax.value_and_grad(loss)(w)
        return w - 0.3 * g, l

    w = jax.device_put(w, NamedSharding(mesh, P("pp")))
    losses = []
    for _ in range(80):
        w, l = train_step(w)
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.75


def test_pipeline_rejects_shape_changing_stage():
    mesh = _mesh(2)

    def bad_stage(w, x):
        return jnp.concatenate([x, x], axis=-1)

    @functools.partial(
        jax.shard_map, mesh=mesh, in_specs=(P("pp"), P()), out_specs=P()
    )
    def f(w, mb):
        return pipeline_apply(bad_stage, w, mb, "pp")

    w = jnp.zeros((2, 4, 4))
    mb = jnp.zeros((4, 2, 4))
    with pytest.raises(ValueError, match="preserve the activation shape"):
        f(w, mb)
