"""Pipeline parallelism must match the sequential layer stack exactly —
forward AND backward (autodiff through the collective schedule) — and
train end-to-end."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from consensusml_tpu.parallel import pipeline_apply, pipeline_last_stage_mean


def _mesh(p):
    return Mesh(np.array(jax.devices("cpu")[:p]), ("pp",))


def _layer(w, x):
    return jnp.tanh(x @ w)


def _stage_fn(stage_params, x):
    # apply this stage's local slice of the layer stack in order
    def body(h, w):
        return _layer(w, h), None

    y, _ = jax.lax.scan(body, x, stage_params)
    return y


def _sequential(all_w, mb):
    def per_mb(x):
        def body(h, w):
            return _layer(w, h), None

        y, _ = jax.lax.scan(body, x, all_w)
        return y

    return jax.vmap(per_mb)(mb)


def _run_pipeline(all_w, mb, p):
    mesh = _mesh(p)

    @jax.jit
    @functools.partial(
        jax.shard_map, mesh=mesh, in_specs=(P("pp"), P()), out_specs=P()
    )
    def f(w, mb):
        outs = pipeline_apply(_stage_fn, w, mb, "pp")
        # replicate the last stage's outputs for comparison
        return pipeline_last_stage_mean(outs, "pp")

    w_sharded = jax.device_put(all_w, NamedSharding(mesh, P("pp")))
    return np.asarray(f(w_sharded, mb))


@pytest.mark.parametrize("p,m", [(2, 4), (4, 8), (8, 8)])
def test_pipeline_matches_sequential_forward(p, m):
    rng = np.random.default_rng(0)
    layers, b, d = 8, 4, 16
    all_w = jnp.asarray(rng.normal(size=(layers, d, d)) * 0.5, jnp.float32)
    mb = jnp.asarray(rng.normal(size=(m, b, d)), jnp.float32)
    want = np.asarray(_sequential(all_w, mb))
    got = _run_pipeline(all_w, mb, p)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_pipeline_gradients_match_sequential():
    rng = np.random.default_rng(1)
    layers, m, b, d, p = 8, 8, 2, 8, 4
    all_w = jnp.asarray(rng.normal(size=(layers, d, d)) * 0.5, jnp.float32)
    mb = jnp.asarray(rng.normal(size=(m, b, d)), jnp.float32)
    tgt = jnp.asarray(rng.normal(size=(m, b, d)), jnp.float32)
    mesh = _mesh(p)

    def seq_loss(w):
        return jnp.mean((_sequential(w, mb) - tgt) ** 2)

    @jax.jit
    @functools.partial(
        jax.shard_map, mesh=mesh, in_specs=P("pp"), out_specs=P("pp")
    )
    def pp_grad(w):
        def loss(w):
            outs = pipeline_apply(_stage_fn, w, mb, "pp")
            per = jnp.mean((outs - tgt) ** 2)
            return pipeline_last_stage_mean(per, "pp")

        return jax.grad(loss)(w)

    w_sharded = jax.device_put(all_w, NamedSharding(mesh, P("pp")))
    got = np.asarray(jax.device_get(pp_grad(w_sharded)))
    want = np.asarray(jax.grad(seq_loss)(all_w))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-6)


def test_pipeline_trains():
    """A pipelined deep tanh stack fits a random mapping (loss decreases)."""
    rng = np.random.default_rng(2)
    layers, m, b, d, p = 4, 8, 4, 8, 4
    w = jnp.asarray(rng.normal(size=(layers, d, d)) * 0.3, jnp.float32)
    mb = jnp.asarray(rng.normal(size=(m, b, d)), jnp.float32)
    tgt = jnp.tanh(jnp.asarray(rng.normal(size=(m, b, d)), jnp.float32))
    mesh = _mesh(p)

    @jax.jit
    @functools.partial(
        jax.shard_map, mesh=mesh, in_specs=P("pp"), out_specs=(P("pp"), P())
    )
    def train_step(w):
        def loss(w):
            outs = pipeline_apply(_stage_fn, w, mb, "pp")
            return pipeline_last_stage_mean(jnp.mean((outs - tgt) ** 2), "pp")

        l, g = jax.value_and_grad(loss)(w)
        return w - 0.3 * g, l

    w = jax.device_put(w, NamedSharding(mesh, P("pp")))
    losses = []
    for _ in range(80):
        w, l = train_step(w)
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.75


def test_pipeline_rejects_shape_changing_stage():
    mesh = _mesh(2)

    def bad_stage(w, x):
        return jnp.concatenate([x, x], axis=-1)

    @functools.partial(
        jax.shard_map, mesh=mesh, in_specs=(P("pp"), P()), out_specs=P()
    )
    def f(w, mb):
        return pipeline_apply(bad_stage, w, mb, "pp")

    w = jnp.zeros((2, 4, 4))
    mb = jnp.zeros((4, 2, 4))
    with pytest.raises(ValueError, match="preserve the activation shape"):
        f(w, mb)


# ---------------------------------------------------------------------------
# PP x gossip-DP composition (VERDICT r3 item 4): pipeline-parallel workers
# inside make_collective_train_step, cross-validated against the simulated
# backend (whose sequential layer scan is the oracle).
# ---------------------------------------------------------------------------


def _pp_loss_fns(layers, d, microbatches):
    """(collective pipelined, simulated sequential) loss_fn pair with
    IDENTICAL math: mean over (M, B/M, d) == mean over (B, d)."""

    def stage_fn(sp, x):
        def body(h, wb):
            w, b = wb
            return jnp.tanh(h @ w + b), None

        return jax.lax.scan(body, x, (sp["w"], sp["b"]))[0]

    def pp_loss(params, model_state, batch, rng):
        x, y = batch["x"], batch["y"]
        mb = x.reshape(microbatches, -1, x.shape[-1])
        yb = y.reshape(microbatches, -1, y.shape[-1])
        outs = pipeline_apply(stage_fn, params["stages"], mb, "pp")
        loss = pipeline_last_stage_mean(jnp.mean((outs - yb) ** 2), "pp")
        return loss, model_state

    def seq_loss(params, model_state, batch, rng):
        def body(h, wb):
            w, b = wb
            return jnp.tanh(h @ w + b), None

        sp = params["stages"]
        out = jax.lax.scan(body, batch["x"], (sp["w"], sp["b"]))[0]
        return jnp.mean((out - batch["y"]) ** 2), model_state

    def init(r):
        kw, kb = jax.random.split(r)
        return {
            "stages": {
                "w": 0.4 * jax.random.normal(kw, (layers, d, d)),
                "b": 0.01 * jax.random.normal(kb, (layers, d)),
            }
        }

    return stage_fn, pp_loss, seq_loss, init


@pytest.mark.parametrize("compressed", [False, True])
def test_pp_composes_with_gossip_dp(compressed):
    """ring(2) x pp=2 over 4 devices: the integrated pipeline-parallel
    train step must match the simulated backend round for round —
    losses, consensus error, and final params."""
    import optax

    from consensusml_tpu.comm import WorkerMesh
    from consensusml_tpu.compress import TopKCompressor
    from consensusml_tpu.consensus import GossipConfig
    from consensusml_tpu.parallel import pipeline_pp_rules
    from consensusml_tpu.topology import RingTopology
    from consensusml_tpu.train import (
        LocalSGDConfig,
        init_stacked_state,
        make_collective_train_step,
        make_simulated_train_step,
    )

    world, layers, d, batch, h, mbs = 2, 4, 16, 8, 2, 4
    topo = RingTopology(world)
    # CHUNK-ALIGNED codec: pp-sharded CHOCO compresses each stage's layer
    # shard locally, so only chunk-local selection (chunk dividing the
    # per-stage leaf size) keeps bit-identical semantics vs the unsharded
    # oracle; a global-per-leaf top-k would select differently per shard
    # (documented in make_collective_train_step). Per-stage w shard =
    # 2*16*16 = 512 = 4 chunks; bias shards stay under one chunk with
    # k >= real elements, so both paths are lossless there.
    from consensusml_tpu.compress import ChunkedTopKCompressor

    comp = (
        ChunkedTopKCompressor(chunk=128, k_per_chunk=64) if compressed else None
    )
    cfg = LocalSGDConfig(
        gossip=GossipConfig(
            topology=topo, compressor=comp, gamma=0.6 if compressed else 1.0
        ),
        optimizer=optax.sgd(0.1),
        h=h,
    )
    _, pp_loss, seq_loss, init = _pp_loss_fns(layers, d, mbs)
    rules = pipeline_pp_rules()

    wmesh = WorkerMesh.create(
        topo,
        devices=jax.devices()[:4],
        model_axes=(("pp", 2),),
        manual_model_axes=("pp",),
    )
    step_c = make_collective_train_step(cfg, pp_loss, wmesh, rules=rules)
    step_s = make_simulated_train_step(cfg, seq_loss)

    state_c = init_stacked_state(cfg, init, jax.random.key(0), world)
    state_s = init_stacked_state(cfg, init, jax.random.key(0), world)
    state_c = wmesh.shard_stacked(state_c, rules=rules)

    rng = np.random.default_rng(0)
    for r in range(3):
        xs = rng.normal(size=(world, h, batch, d)).astype(np.float32)
        ys = np.tanh(rng.normal(size=(world, h, batch, d))).astype(np.float32)
        b = {"x": jnp.asarray(xs), "y": jnp.asarray(ys)}
        state_c, mc = step_c(state_c, wmesh.shard_stacked(b))
        state_s, ms = step_s(state_s, b)
        np.testing.assert_allclose(
            float(mc["loss"]), float(ms["loss"]), rtol=2e-5, err_msg=f"round {r}"
        )
        np.testing.assert_allclose(
            float(mc["consensus_error"]),
            float(ms["consensus_error"]),
            rtol=2e-4,
            atol=1e-6,
            err_msg=f"round {r}",
        )
    for pc, ps in zip(
        jax.tree.leaves(state_c.params), jax.tree.leaves(state_s.params)
    ):
        np.testing.assert_allclose(np.asarray(pc), np.asarray(ps), rtol=3e-5, atol=1e-6)


def test_pp_requires_rules():
    import optax

    from consensusml_tpu.comm import WorkerMesh
    from consensusml_tpu.consensus import GossipConfig
    from consensusml_tpu.topology import RingTopology
    from consensusml_tpu.train import LocalSGDConfig, make_collective_train_step

    topo = RingTopology(2)
    wmesh = WorkerMesh.create(
        topo,
        devices=jax.devices()[:4],
        model_axes=(("pp", 2),),
        manual_model_axes=("pp",),
    )
    cfg = LocalSGDConfig(
        gossip=GossipConfig(topology=topo), optimizer=optax.sgd(0.1), h=1
    )
    with pytest.raises(ValueError, match="rules"):
        make_collective_train_step(cfg, lambda *a: None, wmesh)


def test_pp_rejects_unsupported_features():
    import optax

    from consensusml_tpu.comm import WorkerMesh
    from consensusml_tpu.consensus import FaultConfig, GossipConfig
    from consensusml_tpu.parallel import pipeline_pp_rules
    from consensusml_tpu.topology import RingTopology
    from consensusml_tpu.train import LocalSGDConfig, make_collective_train_step

    topo = RingTopology(2)
    wmesh = WorkerMesh.create(
        topo,
        devices=jax.devices()[:4],
        model_axes=(("pp", 2),),
        manual_model_axes=("pp",),
    )
    cfg = LocalSGDConfig(
        gossip=GossipConfig(topology=topo, faults=FaultConfig(drop_prob=0.1)),
        optimizer=optax.sgd(0.1),
        h=1,
    )
    with pytest.raises(NotImplementedError, match="fault injection"):
        make_collective_train_step(
            cfg, lambda *a: None, wmesh, rules=pipeline_pp_rules()
        )
