"""Test configuration: run everything on a virtual 8-device CPU mesh.

This mirrors the reference's CPU-simulated-workers test backend
(BASELINE.json configs[0]): multi-worker gossip semantics are validated
without a TPU pod by forcing the XLA host platform to expose 8 devices.

Note: this box's axon TPU plugin (sitecustomize in /root/.axon_site)
force-sets ``jax_platforms="axon,cpu"`` at interpreter start, overriding
the JAX_PLATFORMS env var — so we must ALSO override via jax.config after
import. XLA_FLAGS still must be set before the first jax import.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def compat_shard_map():
    """``jax.shard_map`` on new jax, ``jax.experimental.shard_map``
    elsewhere (this box's 0.4.37 only has the experimental path). The one
    version shim the suite shares — a jax bump edits it here once."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm
    from jax.experimental.shard_map import shard_map as sm

    return sm


# ---------------------------------------------------------------------------
# fast/slow test tiers
#
# The full suite takes ~18-20 min on an 8-device virtual CPU mesh (compile
# cost dominates). The FAST tier — `pytest -m "not slow"` — finishes in a
# few minutes and still touches every module's math. Tests measured >=5s
# (pytest --durations on this box) are marked slow here centrally, so the
# tier stays honest as timings drift: re-measure and edit this list.
# Tests may also self-mark with @pytest.mark.slow.
# ---------------------------------------------------------------------------

_SLOW_TESTS = {
    # hybrid/model-parallel cross-validation (shard_map compiles)
    "test_llama_tp_matches_simulated", "test_gpt2_tp_rules_apply",
    # ResNet full-model compiles + ring training
    "test_config2_resnet_ring_training_smoke",
    "test_resnet50_param_count_and_shapes",
    "test_resnet_bn_state_updates_in_train_mode",
    "test_resnet_cifar_stem_keeps_resolution",
    # MoE (expert-parallel compiles)
    "test_moe_ep_matches_simulated", "test_moe_local_sgd_trains",
    "test_moe_forward_shapes_and_aux", "test_moe_interleave",
    "test_moe_causality", "test_routing_no_drop_when_capacity_ample",
    # codec convergence loops
    "test_choco_converges_with_codec", "test_stochastic_codec_backends_agree",
    # CLI subprocess runs (fresh interpreter + compile each)
    "test_train_checkpoint_resume", "test_worker_single_process_forwards",
    "test_train_mnist_end_to_end", "test_train_unknown_config",
    "test_train_list", "test_train_requires_config",
    "test_train_llama_lora_model_axes_tp2",
    "test_train_model_axes_rejected_without_rules",
    "test_train_model_axes_bad_syntax",
    "test_train_model_axes_multi_axis_rejected",
    "test_train_model_axes_zero_rejected",
    "test_train_topology_override_hierarchical",
    "test_train_native_loader",
    "test_train_native_loader_with_data_dir",
    "test_train_topology_override_bad_name",
    "test_train_lr_schedule_flags",
    "test_train_codec_override",
    "test_train_eval_every",
    "test_lora_grad_clip_ignores_frozen_base",
    # time-varying topology convergence
    "test_onepeer_beats_ring_consensus_decay",
    "test_choco_collective_matches_simulated_onepeer",
    "test_symmetric_time_varying_with_faults_runs",
    "test_onepeer_with_choco_compression_converges",
    "test_collective_matches_simulated_onepeer",
    # transformer configs (full forward/backward compiles)
    "test_config5_gpt2_compressed_gossip", "test_config4_llama_lora_torus",
    "test_config3_bert_local_sgd_h8", "test_bert_shapes",
    "test_llama_forward_and_gqa", "test_lora_mask_selects_adapters_only",
    # evaluation over stacked replicas
    "test_lm_configs_expose_eval",
    "test_evaluate_reports_per_worker_and_mean_model", "test_cli_eval",
    # faults / outer-optimizer cross-validation
    "test_collective_matches_simulated_under_dropout",
    "test_collective_matches_simulated_slowmo",
    "test_slowmo_converges_and_momentum_engages",
    # CHOCO contraction sweeps
    "test_choco_contracts_and_preserves_mean",
    "test_choco_collective_matches_simulated",
    # hierarchical convergence loop
    "test_hierarchical_with_faults_converges",
    # elastic resize (each builds + trains a stacked state first)
    "test_training_continues_after_resize_both_ways",
    "test_resize_resets_choco_state_at_new_world",
    "test_grow_joiners_start_at_consensus_mean",
    "test_shrink_keeps_survivor_replicas_exactly",
    # round-2 additions measured >=5s (2026-07-30 re-tier)
    "test_resnet_fused_impl_matches_flax_impl",
    "test_sequence_parallel_training_end_to_end",
    "test_collective_matches_simulated_hierarchical",
    "test_gpt2_causality",
    "test_odd_sizes_and_padding",
    "test_zero_lr_reduces_to_plain_gossip",
    "test_mean_model_at_consensus_equals_workers",
    "test_cli_profile_dir",
    "test_gpt2_fullseq_forward_uses_blockwise_without_oom",
    # two-controller jax.distributed run (subprocess pair + compiles)
    "test_two_process_collective_training",
    "test_two_process_checkpoint_and_eval",
    # round-4 hang-guard subprocess tests (fresh interpreters / heavy imports)
    "test_cli_exit_codes",
    "test_train_device_tpu_wedged_gives_clean_error",
    "test_train_device_tpu_cpu_only_gives_clean_error",
    "test_bench_emits_headline_json_when_budget_exhausted",
    "test_bench_wedged_preflight_skips_tpu_sections",
    "test_bench_sigterm_lands_partial_json",
    "test_train_gossip_steps_and_gamma",
    "test_train_gamma_rejected_on_exact_config",
    # round-5 serving additions measured >=5s (token-by-token python
    # loops / double engine runs). The acceptance-critical serving tests
    # (test_e2e_train_export_serve_demo, the golden parity test, the
    # 8-stream zero-recompile test) deliberately STAY in the fast tier.
    "test_incremental_decode_matches_full_forward",
    "test_decode_is_deterministic_across_batching",
    "test_export_roundtrip_and_meta",
    # round-6 fused paged-attention additions measured >=5s. The
    # acceptance-critical kernel-tier tests (engine stream parity both
    # families, spec-engine parity, tight-pool preemption, the fuzz
    # parity sweeps) deliberately STAY in the fast tier; these two are
    # covered by them at engine level and pin secondary surfaces.
    "test_register_costs_adds_fused_rows_side_by_side",
    "test_model_decode_step_parity_per_family",
    # round-7 re-tier: fast tier re-measured at ~17 min on this box, over
    # the verify budget. Tests >=10s with a fast-tier sibling or e2e
    # covering the same surface move here. The acceptance-critical set
    # (paged-vs-slot parity [gpt2], fused stream/spec parity both
    # families, zero-recompile contract, hot-swap e2e, wide-event
    # cost-join pin + multi-tenant e2e) deliberately STAYS fast.
    "test_sampled_engine_streams_replay_deterministically",
    "test_tight_pool_preempts_mid_draft_stream_by_recompute",
    "test_close_from_another_thread_unblocks_waiting_consumer",
    "test_cli_all_exits_zero_on_repo",
    "test_llama_loss_fn_parity",
    "test_perf_sweep_fed_input_smoke",
    "test_profile_endpoint_single_flight_and_rotation",
    "test_profile_capture_parses_via_xprof_summary_json",
    "test_engine_without_ledger_still_emits_unjoined",
    # round-8 fleet tier: each spawns 2-3 real in-process engines (one
    # warmup compile per replica). The fast tier pins the same router/
    # controller logic on stub servers and fake handles (test_fleet.py).
    "test_fleet_e2e_placement_and_kill_redispatch",
    "test_fleet_e2e_canary_promote_and_rollback",
    "test_fleet_e2e_affinity_tracks_single_engine_prefix_rate",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.name.split("[")[0] in _SLOW_TESTS:
            item.add_marker(pytest.mark.slow)
