"""Test configuration: run everything on a virtual 8-device CPU mesh.

This mirrors the reference's CPU-simulated-workers test backend
(BASELINE.json configs[0]): multi-worker gossip semantics are validated
without a TPU pod by forcing the XLA host platform to expose 8 devices.
Must run before the first ``import jax`` anywhere in the test process.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
