"""Test configuration: run everything on a virtual 8-device CPU mesh.

This mirrors the reference's CPU-simulated-workers test backend
(BASELINE.json configs[0]): multi-worker gossip semantics are validated
without a TPU pod by forcing the XLA host platform to expose 8 devices.

Note: this box's axon TPU plugin (sitecustomize in /root/.axon_site)
force-sets ``jax_platforms="axon,cpu"`` at interpreter start, overriding
the JAX_PLATFORMS env var — so we must ALSO override via jax.config after
import. XLA_FLAGS still must be set before the first jax import.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
