"""Runtime lock-order sanitizer e2e (ISSUE 14 acceptance).

The paged serving engine's submit / decode / hot-swap / scrape /
recompute-preempt paths run CONCURRENTLY under the instrumented lock
wrappers and the schedule-fuzz harness, and the observed acquisition
graph must be (a) acyclic — zero lock-order inversions — and (b) a
subgraph of the static model `analysis/lockorder.py` builds from the
source. Unit tests for the sanitizer mechanics (cycle detection from
sequential ABBA, RLock re-entry exemption, non-LIFO release) ride
alongside.
"""

import os
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from consensusml_tpu.analysis import lockdep, lockorder
from consensusml_tpu.analysis.lockdep import LockOrderSanitizer, fuzz_schedule

pytestmark = pytest.mark.analysis

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# sanitizer mechanics
# ---------------------------------------------------------------------------


def test_abba_order_is_flagged_without_a_deadlock():
    """Two locks taken in opposite orders SEQUENTIALLY (no deadlock ever
    manifests) still produce a cycle in the observed graph — the whole
    point of the sanitizer vs waiting for the hang."""
    with LockOrderSanitizer() as san:
        class A:
            def __init__(self):
                self._lock = threading.Lock()

        class B:
            def __init__(self):
                self._lock = threading.Lock()

        a, b = A(), B()
        with a._lock:
            with b._lock:
                pass
        with b._lock:
            with a._lock:
                pass
    assert ("A._lock", "B._lock") in san.observed_edges()
    assert ("B._lock", "A._lock") in san.observed_edges()
    problems = san.check()
    assert any("cycle" in p for p in problems), problems


def test_rlock_reentry_is_exempt_and_named():
    with LockOrderSanitizer() as san:
        class R:
            def __init__(self):
                self._lock = threading.RLock()

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._lock:
                    pass

        R().outer()
    assert san.check() == []
    assert san.reentries.get("R._lock", 0) >= 1


def test_unmodeled_edge_against_static_model_is_flagged():
    """An observed edge between package-named locks that the static
    model does not contain is a violation (the model drifted or the
    code took a path the AST cannot see)."""
    static = lockorder.analyze_sources(
        [(
            "fx.py",
            "import threading\n"
            "class X:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n",
        )]
    )
    with LockOrderSanitizer() as san:
        # fake two "package" locks by planting names directly
        l1, l2 = threading.Lock(), threading.Lock()
        with san._state:
            san._names[id(l1)] = ("X._lock", True)
            san._names[id(l2)] = ("Y._lock", True)
        static.kinds.setdefault("X._lock", "Lock")
        static.kinds.setdefault("Y._lock", "Lock")
        with l1:
            with l2:
                pass
    problems = san.check(static)
    assert any("NOT in the static lock model" in p for p in problems), problems


def test_condition_over_wrapped_rlock_waits_and_notifies():
    """threading.Condition binds the wrapped lock's private protocol:
    wait()/notify() must work over a sanitized RLock (and Event/Queue,
    which build Conditions internally), with the held stack surviving
    wait()'s full release/re-acquire."""
    with LockOrderSanitizer() as san:
        cond = threading.Condition(threading.RLock())
        ev = threading.Event()
        got = []

        def waiter():
            with cond:
                got.append("in")
                assert cond.wait(timeout=10)
                got.append("woke")

        t = threading.Thread(target=waiter, daemon=True)
        t.start()
        while not got:
            time.sleep(0.001)
        with cond:
            cond.notify()
        t.join(timeout=10)
        assert got == ["in", "woke"]
        # Event uses Condition(Lock()) internally: same protocol path
        ev.set()
        assert ev.wait(timeout=1)
    assert san.check() == []


def test_fuzz_schedule_reraises_and_restores_interval():
    prev = __import__("sys").getswitchinterval()
    with pytest.raises(RuntimeError, match="boom"):
        fuzz_schedule(
            [lambda: None, lambda: (_ for _ in ()).throw(RuntimeError("boom"))],
            seed=1,
        )
    assert __import__("sys").getswitchinterval() == prev


# ---------------------------------------------------------------------------
# the acceptance e2e: engine + watcher + scraper under fuzz
# ---------------------------------------------------------------------------


def _tiny_gpt2():
    from consensusml_tpu.models.gpt2 import GPT2Config, GPT2LM

    return GPT2LM(
        config=GPT2Config(
            vocab_size=64, hidden=32, layers=2, heads=2, max_len=32,
            dropout=0.0,
        )
    )


def test_serving_engine_watcher_scraper_inversion_free(tmp_path):
    """submit / decode / hot-swap / scrape / preempt concurrently under
    the sanitizer + fuzz harness: zero observed lock-order inversions,
    and every package-lock nesting is in the static model."""
    from consensusml_tpu.obs import get_registry, get_request_registry
    from consensusml_tpu.serve import Engine, ServeConfig
    from consensusml_tpu.serve.export import _write_meta, serving_meta
    from consensusml_tpu.serve.pool.hotswap import GenerationWatcher

    model = _tiny_gpt2()
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    art = str(tmp_path / "art")
    os.makedirs(art)
    _write_meta(art, {"generation": 1, "config_name": "lockdep-fixture"})

    with LockOrderSanitizer(fuzz=0.02, seed=7) as san:
        # constructed INSIDE the window: engine queue/events, watcher
        # lock, and any metric child created fresh all get wrapped
        eng = Engine(
            model, params,
            ServeConfig(
                num_slots=4, max_len=32, max_new_tokens=24, num_blocks=10,
            ),
        )
        loader_calls = []

        def loader(path):
            loader_calls.append(path)
            return serving_meta(path), params, None

        watcher = GenerationWatcher(
            art, current_generation=0, poll_s=0.01, loader=loader
        )
        eng._watcher = watcher

        def submitter():
            # one concurrent WAVE per submitter: 8 streams contend for
            # 4 slots and 10 blocks, forcing recompute preemption
            rng = np.random.default_rng(1)
            handles = [
                eng.submit(rng.integers(0, 63, size=n).tolist(), 24)
                for n in (3, 7, 8, 8)
            ]
            for h in handles:
                assert len(h.result(timeout=120).tokens) == 24

        def scraper():
            reg, rt = get_registry(), get_request_registry()
            for _ in range(120):
                reg.to_prometheus()
                rt.snapshot()
                eng.stats()
                time.sleep(0.002)

        def swapper():
            from consensusml_tpu.serve.export import bump_generation

            for _ in range(3):
                time.sleep(0.05)
                bump_generation(art)

        try:
            fuzz_schedule(
                [submitter, submitter, scraper, swapper],
                seed=3, timeout_s=240,
            )
        finally:
            eng.shutdown(drain=True, timeout=60)

    # every path actually ran: streams completed (asserted inline), the
    # watcher staged + the engine flipped at least one generation, the
    # tight pool forced at least one recompute preemption
    stats = eng.stats()
    assert eng.generation >= 1 and loader_calls, (
        eng.generation, loader_calls
    )
    assert stats["evictions"] >= 1, stats
    assert san.acquisitions > 100
    # THE acceptance assertions: acyclic observed order, and observed
    # package-lock nesting ⊆ the static lockorder model
    san.assert_clean(static=lockorder.static_model(REPO))
