"""Multi-slice hierarchical (ring-of-rings) gossip topology.

SURVEY.md §5 "DCN for multi-slice if ever needed": inner-ring phases ride
ICI every round, the inter-slice outer ring fires 1-in-outer_every
rounds. These tests pin the math (doubly-stochastic phases, per-period
contraction, wire-cost ratio) and backend agreement on a 2x4 virtual
mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from consensusml_tpu.comm import WorkerMesh, slice_major_devices
from consensusml_tpu.consensus import GossipConfig
from consensusml_tpu.data import SyntheticClassification, round_batches
from consensusml_tpu.models import MLP, mlp_loss_fn
from consensusml_tpu.topology import HierarchicalTopology, topology_from_name
from consensusml_tpu.train import (
    LocalSGDConfig,
    init_stacked_state,
    make_collective_train_step,
    make_simulated_train_step,
)


def test_phases_structure_and_double_stochasticity():
    topo = HierarchicalTopology(slices=2, inner=4, outer_every=3)
    assert topo.period == 3
    assert topo.mesh_shape == (2, 4)
    # phases 0..K-2 move along the inner axis only, phase K-1 outer only
    for p in topo.phases[:-1]:
        assert {s.axis for s in p.shifts} == {1}
    assert {s.axis for s in topo.phases[-1].shifts} == {0}
    for w in topo.phase_matrices():
        np.testing.assert_allclose(w.sum(0), 1.0, atol=1e-12)
        np.testing.assert_allclose(w.sum(1), 1.0, atol=1e-12)
        assert (w >= 0).all()


def test_period_contracts_to_consensus():
    topo = HierarchicalTopology(slices=2, inner=4, outer_every=4)
    gap = topo.spectral_gap()
    assert 0 < gap <= 1
    # inner-only phases never mix across slices: a slice-wise-constant
    # disagreement survives until the outer phase fires
    w_inner = topo.phase_matrices()[0]
    x = np.kron(np.array([1.0, -1.0]), np.ones(4))  # +1 on slice0, -1 on slice1
    np.testing.assert_allclose(w_inner @ x, x, atol=1e-12)
    w_eff = topo.effective_matrix()
    assert np.linalg.norm(w_eff @ x - x.mean()) < np.linalg.norm(x)


def test_outer_round_wire_cost_is_amortized():
    """The design point: only 1 round in outer_every touches the slow
    inter-slice axis."""
    topo = HierarchicalTopology(slices=4, inner=8, outer_every=5)
    outer_rounds = sum(
        1 for p in topo.phases if any(s.axis == 0 for s in p.shifts)
    )
    assert outer_rounds == 1 and topo.period == 5


def test_from_name_and_validation():
    topo = topology_from_name("hierarchical", 8, slices=2, outer_every=2)
    assert isinstance(topo, HierarchicalTopology)
    assert topo.mesh_shape == (2, 4)
    with pytest.raises(ValueError, match="slices"):
        topology_from_name("hierarchical", 8)
    with pytest.raises(ValueError, match="divide"):
        topology_from_name("hierarchical", 8, slices=3)


def test_slice_major_devices_is_safe_without_slices():
    devs = slice_major_devices()
    assert len(devs) == len(jax.devices())
    assert [d.id for d in devs] == sorted(d.id for d in devs)


def test_collective_matches_simulated_hierarchical():
    topo = HierarchicalTopology(slices=2, inner=4, outer_every=2)
    model = MLP(hidden=16)
    cfg = LocalSGDConfig(
        gossip=GossipConfig(topology=topo), optimizer=optax.adam(1e-2), h=1
    )
    init = lambda rng: model.init(rng, jnp.zeros((1, 28, 28, 1)))["params"]
    loss_fn = mlp_loss_fn(model)
    data = SyntheticClassification(n=512)

    sim_step = make_simulated_train_step(cfg, loss_fn)
    wmesh = WorkerMesh.create(topo, devices=slice_major_devices()[:8])
    col_step = make_collective_train_step(cfg, loss_fn, wmesh)

    state = init_stacked_state(cfg, init, jax.random.key(3), 8)
    sim_state, col_state = state, wmesh.shard_stacked(state)
    for batch in round_batches(data, 8, h=1, batch=8, rounds=4):
        sim_state, sm = sim_step(sim_state, batch)
        col_state, cm = col_step(col_state, batch)
        np.testing.assert_allclose(
            float(sm["consensus_error"]), float(cm["consensus_error"]), rtol=1e-4
        )
    for a, b in zip(jax.tree.leaves(sim_state.params), jax.tree.leaves(col_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_hierarchical_training_converges():
    topo = HierarchicalTopology(slices=2, inner=2, outer_every=3)
    model = MLP(hidden=16)
    cfg = LocalSGDConfig(
        gossip=GossipConfig(topology=topo), optimizer=optax.adam(5e-3), h=1
    )
    step = make_simulated_train_step(cfg, mlp_loss_fn(model))
    state = init_stacked_state(
        cfg,
        lambda rng: model.init(rng, jnp.zeros((1, 28, 28, 1)))["params"],
        jax.random.key(0),
        4,
    )
    data = SyntheticClassification(n=512)
    losses, errs = [], []
    for batch in round_batches(data, 4, h=1, batch=16, rounds=30):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
        errs.append(float(m["consensus_error"]))
    assert losses[-1] < losses[0] * 0.7
    assert errs[-1] < errs[2]


def test_outer_every_one_rejected_when_inner_mixing_needed():
    with pytest.raises(ValueError, match="never mix"):
        HierarchicalTopology(slices=2, inner=4, outer_every=1)
    # inner=1 has nothing to mix inside a slice: outer-only is fine
    topo = HierarchicalTopology(slices=4, inner=1, outer_every=1)
    assert topo.period == 1


def test_from_name_rejects_nonpositive_slices():
    with pytest.raises(ValueError, match="positive"):
        topology_from_name("hierarchical", 8, slices=0)


def test_hierarchical_with_faults_converges():
    """Hierarchical phases are symmetric rings, so receive-side fault
    masking stays mean-preserving on this topology."""
    from consensusml_tpu.consensus import FaultConfig

    topo = HierarchicalTopology(slices=2, inner=2, outer_every=2)
    model = MLP(hidden=16)
    cfg = LocalSGDConfig(
        gossip=GossipConfig(topology=topo, faults=FaultConfig(drop_prob=0.2)),
        optimizer=optax.adam(5e-3),
        h=1,
    )
    step = make_simulated_train_step(cfg, mlp_loss_fn(model))
    state = init_stacked_state(
        cfg,
        lambda rng: model.init(rng, jnp.zeros((1, 28, 28, 1)))["params"],
        jax.random.key(1),
        4,
    )
    data = SyntheticClassification(n=512)
    losses = []
    for batch in round_batches(data, 4, 1, 16, 25):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
        assert np.isfinite(losses[-1])
    assert losses[-1] < losses[0] * 0.8


def test_hierarchical_with_pushsum_mean_exact():
    """Push-sum on the hierarchical graph: the de-biased mean is conserved
    through inner AND outer phases."""
    from consensusml_tpu.consensus import ConsensusEngine

    topo = HierarchicalTopology(slices=2, inner=4, outer_every=2)
    eng = ConsensusEngine(GossipConfig(topology=topo, push_sum=True))
    x = jnp.asarray(np.random.default_rng(4).normal(size=(8, 5)), jnp.float32)
    state = eng.init_state({"x": x}, world_size=8)
    params = {"x": x}
    from consensusml_tpu.comm import simulated

    w_all = simulated.phase_matrices(topo)
    mean0 = float(jnp.mean(x))
    for t in range(6):
        params, state = eng.round_simulated(params, state, w_all[t % topo.period])
        # network mean of the de-biased variable stays the initial mean
        z, w = params["x"], state.w
        est = float(jnp.mean(z * w[:, None]) )
        np.testing.assert_allclose(est, mean0, rtol=1e-5)
