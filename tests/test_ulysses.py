"""Ulysses all-to-all attention must equal single-device attention on the
gathered sequence, and agree with ring attention (the two SP strategies
are interchangeable exact algorithms)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from consensusml_tpu.models.attention import dot_product_attention
from consensusml_tpu.parallel import ring_attention, ulysses_attention


def _mesh(n):
    return Mesh(np.array(jax.devices("cpu")[:n]), ("sp",))


def _run(fn, q, k, v, n, causal):
    mesh = _mesh(n)
    shard = NamedSharding(mesh, P(None, "sp"))

    @jax.jit
    @jax.shard_map(mesh=mesh, in_specs=P(None, "sp"), out_specs=P(None, "sp"))
    def f(q, k, v):
        return fn(q, k, v, "sp", causal=causal)

    return np.asarray(f(*(jax.device_put(x, shard) for x in (q, k, v))))


@pytest.mark.parametrize("causal", [False, True], ids=["full", "causal"])
@pytest.mark.parametrize("n", [2, 4])
def test_ulysses_matches_dense(causal, n):
    rng = np.random.default_rng(0)
    b, s, h, d = 2, 32, 4, 16
    q, k, v = (
        jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32) for _ in range(3)
    )
    want = np.asarray(
        dot_product_attention(q, k, v, causal=causal, dtype=jnp.float32)
    )
    got = _run(ulysses_attention, q, k, v, n, causal)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_ulysses_matches_ring():
    rng = np.random.default_rng(1)
    b, s, h, d = 1, 64, 8, 8
    q, k, v = (
        jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32) for _ in range(3)
    )
    got_u = _run(ulysses_attention, q, k, v, 8, True)
    got_r = _run(ring_attention, q, k, v, 8, True)
    np.testing.assert_allclose(got_u, got_r, rtol=2e-5, atol=2e-5)


def test_ulysses_bf16():
    rng = np.random.default_rng(2)
    b, s, h, d = 1, 16, 4, 8
    q, k, v = (
        jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.bfloat16) for _ in range(3)
    )
    want = np.asarray(
        dot_product_attention(q, k, v, causal=True, dtype=jnp.bfloat16), np.float32
    )
    got = _run(ulysses_attention, q, k, v, 4, True).astype(np.float32)
    np.testing.assert_allclose(got, want, rtol=0.05, atol=0.05)


def test_ulysses_rejects_indivisible_heads():
    rng = np.random.default_rng(3)
    b, s, h, d = 1, 16, 2, 8  # 2 heads over 4 devices: invalid
    q, k, v = (
        jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32) for _ in range(3)
    )
    with pytest.raises(ValueError, match="divisible"):
        _run(ulysses_attention, q, k, v, 4, False)
