"""tools/bench_diff.py — the bench regression sentinel (ISSUE 10).

Schema-smoke in tier-1 so the tool can't rot: it must run CLEAN against
the checked-in BENCH_r0*.json trajectory, fail loudly on a synthetic
regression and on a blown absolute budget, and its built-in spec must
stay well-formed.
"""

import importlib.util
import json
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tool():
    spec = importlib.util.spec_from_file_location(
        "bench_diff", os.path.join(REPO, "tools", "bench_diff.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_default_spec_is_well_formed():
    mod = _tool()
    assert mod.DEFAULT_SPEC
    for entry in mod.DEFAULT_SPEC:
        assert entry["direction"] in ("up", "down", "max", "min")
        if entry["direction"] in ("max", "min"):
            assert "bound" in entry
        else:
            assert entry.get("tol_pct", 0) >= 0
    # the documented observability budgets are enforced as absolutes
    keys = {e["key"] for e in mod.DEFAULT_SPEC}
    assert "observability.link_probe_overhead_pct" in keys
    assert "observability.request_tracing_overhead_pct" in keys
    # the alerting & history plane (ISSUE 15): amortized tick budget
    # plus the zero-false-firing gate on the default ruleset
    assert "observability.alerting_overhead_pct" in keys
    assert "observability.alerts_fired_on_healthy_run" in keys
    # the cost-attribution plane (ISSUE 11): run-time overhead budget,
    # per-executable compile budgets, and the every-workload
    # expected-vs-measured presence gate
    assert "attribution.attribution_overhead_pct" in keys
    assert "attribution.expected_vs_measured_missing" in keys
    for exe in ("train_step", "gossip_round", "serve_decode",
                "serve_prefill_max", "spec_propose", "spec_verify"):
        assert f"attribution.compile_ms.{exe}" in keys
    # the speculative serving block (ISSUE 13): gain floor + trajectory
    # direction, acceptance floor, zero-recompile gates on both engines
    assert "serving.spec.spec_tokens_per_sec_gain" in keys
    assert "serving.spec.spec.acceptance_rate" in keys
    assert "serving.spec.spec.zero_recompiles_after_warmup" in keys
    assert "serving.spec.baseline.zero_recompiles_after_warmup" in keys
    # the concurrency-correctness plane (ISSUE 14): per-pass wall
    # budgets for the AST passes, the lockdep smoke budget, zero active
    # findings
    for p in ("host_sync", "locks", "threads", "lockorder", "docs_drift"):
        assert f"analysis.pass_seconds.{p}" in keys
    assert "analysis.lockdep_smoke_seconds" in keys
    assert "analysis.active_findings" in keys
    # the protocol-model + lifecycle passes (ISSUE 19): the lifecycle
    # escape lint rides the 2 s AST budget, the exhaustive model
    # checker holds a 30 s wall budget of its own
    assert "analysis.pass_seconds.lifecycle" in keys
    assert "analysis.pass_seconds.model" in keys
    model_bounds = {e["bound"] for e in mod.DEFAULT_SPEC
                    if e["key"] == "analysis.pass_seconds.model"}
    assert model_bounds == {30.0}
    # the fused kernel tier (ISSUE 16): bit-exactness + HBM-bytes gates
    # on the serving fused_attention block, floor-ratio budgets (down
    # trajectory AND absolute ceiling) per hot-path stage, compile
    # walls on the two fused executables
    assert "serving.fused_attention.bit_exact" in keys
    assert "serving.fused_attention.hbm_bytes_ratio" in keys
    for stage in ("serve_decode", "serve_decode_fused", "serve_prefill",
                  "spec_verify", "spec_verify_fused"):
        key = f"attribution.floor_ratio.{stage}"
        dirs = {e["direction"] for e in mod.DEFAULT_SPEC
                if e["key"] == key}
        assert dirs == {"down", "max"}, key
    assert "attribution.compile_ms.serve_decode_fused" in keys
    assert "attribution.compile_ms.spec_verify_fused" in keys
    # the wide-event accounting plane (ISSUE 17): per-terminal emit
    # overhead budget plus the rollup-must-balance gate
    assert "observability.wide_event_overhead_pct" in keys
    assert "observability.tenant_rollup_mismatch" in keys
    # the fleet tier (ISSUE 20): zero lost streams, router overhead
    # under 1% of a p50 request, scored placement no worse than
    # round-robin on the imbalanced mix, zero recompiles after warmup on
    # every replica, canary promoted inside the soak wall budget
    assert "fleet.lost_streams" in keys
    assert "fleet.router_overhead_pct" in keys
    assert "fleet.placement_ttft_ratio" in keys
    assert "fleet.zero_recompiles_after_warmup" in keys
    assert "fleet.canary_promoted" in keys
    assert "fleet.canary_soak_wall_s" in keys


def test_wide_event_gates_enforced_on_fresh_result(tmp_path, capsys):
    """A fresh bench whose wide-event plane blows the emit budget or
    whose rollup fails to re-derive the engine totals fails; the
    healthy shape passes."""
    mod = _tool()
    fresh = {
        "parsed": {"value": 2554.1, "vs_baseline": 1.02},
        "observability": {
            "wide_event_overhead_pct": 3.2,
            "tenant_rollup_mismatch": 4,
        },
    }
    path = tmp_path / "fresh.json"
    path.write_text(json.dumps(fresh))
    rc = mod.main([str(path), "--json", "-"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    failed = {r["key"] for r in doc["rows"] if r["status"] == "regression"}
    assert "observability.wide_event_overhead_pct" in failed
    assert "observability.tenant_rollup_mismatch" in failed

    healthy = {
        "parsed": {"value": 2554.1, "vs_baseline": 1.02},
        "observability": {
            "wide_event_overhead_pct": 0.04,
            "tenant_rollup_mismatch": 0,
        },
    }
    path2 = tmp_path / "healthy.json"
    path2.write_text(json.dumps(healthy))
    rc = mod.main([str(path2), "--json", "-"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    ok = {r["key"]: r["status"] for r in doc["rows"]}
    assert ok["observability.wide_event_overhead_pct"] == "ok"
    assert ok["observability.tenant_rollup_mismatch"] == "ok"


def test_fleet_gates_enforced_on_fresh_result(tmp_path, capsys):
    """A fresh bench that lost an accepted stream, blew the router
    overhead budget, or whose canary never promoted fails; the healthy
    fleet shape passes every gate."""
    mod = _tool()

    def run(fleet):
        fresh = {
            "parsed": {"value": 2554.1, "vs_baseline": 1.02},
            "fleet": fleet,
        }
        path = tmp_path / "fresh.json"
        path.write_text(json.dumps(fresh))
        rc = mod.main([str(path), "--json", "-"])
        return rc, json.loads(capsys.readouterr().out)

    healthy = {
        "lost_streams": 0,
        "router_overhead_pct": 0.2,
        "placement_ttft_ratio": 0.7,
        "zero_recompiles_after_warmup": True,
        "canary_promoted": True,
        "canary_soak_wall_s": 3.5,
    }
    rc, doc = run(healthy)
    assert rc == 0, doc
    blown = dict(
        healthy,
        lost_streams=1,
        router_overhead_pct=2.0,
        placement_ttft_ratio=1.4,
        canary_promoted=False,
    )
    rc, doc = run(blown)
    assert rc == 1
    failed = {r["key"] for r in doc["rows"] if r["status"] == "regression"}
    assert "fleet.lost_streams" in failed
    assert "fleet.router_overhead_pct" in failed
    assert "fleet.placement_ttft_ratio" in failed
    assert "fleet.canary_promoted" in failed
    ok = {r["key"]: r["status"] for r in doc["rows"]}
    assert ok["fleet.zero_recompiles_after_warmup"] == "ok"
    assert ok["fleet.canary_soak_wall_s"] == "ok"


def test_analysis_budgets_enforced_on_fresh_result(tmp_path, capsys):
    """A fresh bench whose analysis section blows a pass-time budget,
    the lockdep smoke budget, or reports an active finding fails."""
    mod = _tool()
    fresh = {
        "parsed": {"value": 2554.1, "vs_baseline": 1.02},
        "analysis": {
            "pass_seconds": {
                "host_sync": 0.6, "locks": 0.4, "threads": 9.0,
                "lockorder": 0.4, "docs_drift": 0.5,
                "lifecycle": 3.1, "model": 29.0,
            },
            "active_findings": 2,
            "lockdep_smoke_seconds": 45.0,
        },
    }
    path = tmp_path / "fresh.json"
    path.write_text(json.dumps(fresh))
    rc = mod.main([str(path), "--json", "-"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    failed = {r["key"] for r in doc["rows"] if r["status"] == "regression"}
    assert "analysis.pass_seconds.threads" in failed
    assert "analysis.pass_seconds.lifecycle" in failed
    assert "analysis.active_findings" in failed
    assert "analysis.lockdep_smoke_seconds" in failed
    ok = {r["key"]: r["status"] for r in doc["rows"]}
    assert ok["analysis.pass_seconds.host_sync"] == "ok"
    # 29 s of model checking is within its own (30 s) budget
    assert ok["analysis.pass_seconds.model"] == "ok"


def test_min_direction_enforces_floors(tmp_path, capsys):
    """A fresh bench whose speculative block loses its tokens/s gain,
    acceptance floor, or zero-recompile gate fails; a healthy block
    passes. Booleans gate as min-1 floors (true == 1)."""
    mod = _tool()

    def run(spec_block):
        fresh = {
            "parsed": {"value": 2554.1, "vs_baseline": 1.02},
            "serving": {"spec": spec_block},
        }
        path = tmp_path / "fresh.json"
        path.write_text(json.dumps(fresh))
        rc = mod.main([str(path), "--repo-root", REPO])
        return rc, capsys.readouterr().out

    healthy = {
        "spec_tokens_per_sec_gain": 2.3,
        "baseline": {"zero_recompiles_after_warmup": True},
        "spec": {
            "acceptance_rate": 1.0,
            "zero_recompiles_after_warmup": True,
        },
    }
    rc, _out = run(healthy)
    assert rc == 0
    bad = json.loads(json.dumps(healthy))
    bad["spec_tokens_per_sec_gain"] = 1.1  # floor is 1.5
    bad["spec"]["acceptance_rate"] = 0.5  # proxy floor is 0.95
    bad["spec"]["zero_recompiles_after_warmup"] = False
    rc, out = run(bad)
    assert rc == 1
    assert "below the absolute floor" in out


def test_attribution_budgets_enforced_on_fresh_result(tmp_path, capsys):
    """A fresh bench whose attribution section blows the run-time
    budget or misses an expected-vs-measured pairing fails the gate."""
    mod = _tool()
    fresh = {
        "parsed": {"value": 2554.1, "vs_baseline": 1.02},
        "attribution": {
            "attribution_overhead_pct": 3.0,  # budget is <1%
            "expected_vs_measured_missing": 1,  # must be 0
            "compile_ms": {"train_step": 500.0},
        },
    }
    path = tmp_path / "fresh.json"
    path.write_text(json.dumps(fresh))
    rc = mod.main([str(path), "--json", "-"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    failed = {r["key"] for r in doc["rows"] if r["status"] == "regression"}
    assert "attribution.attribution_overhead_pct" in failed
    assert "attribution.expected_vs_measured_missing" in failed
    ok = {
        r["key"]: r["status"] for r in doc["rows"]
    }
    assert ok["attribution.compile_ms.train_step"] == "ok"


def test_fused_attention_gates_enforced_on_fresh_result(tmp_path, capsys):
    """A fresh bench whose fused block lost bit-exactness, touched MORE
    HBM bytes than the gather path, or whose floor ratios blew their
    absolute ceilings fails; a healthy block passes the same gates."""
    mod = _tool()

    def run(serving, attribution):
        fresh = {
            "parsed": {"value": 2554.1, "vs_baseline": 1.02},
            "serving": serving,
            "attribution": attribution,
        }
        path = tmp_path / "fresh.json"
        path.write_text(json.dumps(fresh))
        rc = mod.main([str(path), "--json", "-"])
        return rc, json.loads(capsys.readouterr().out)

    healthy_ratio = {
        "serve_decode": 7.6, "serve_decode_fused": 6.0,
        "serve_prefill": 5.0, "spec_verify": 5.7,
        "spec_verify_fused": 8.3,
    }
    rc, doc = run(
        {"fused_attention": {"bit_exact": 1, "hbm_bytes_ratio": 0.93}},
        {"floor_ratio": dict(healthy_ratio)},
    )
    assert rc == 0, doc
    blown = dict(healthy_ratio)
    blown["serve_decode_fused"] = 250.0  # ceiling is 100x floor
    rc, doc = run(
        {"fused_attention": {"bit_exact": 0, "hbm_bytes_ratio": 1.2}},
        {"floor_ratio": blown},
    )
    assert rc == 1
    failed = {r["key"] for r in doc["rows"] if r["status"] == "regression"}
    assert "serving.fused_attention.bit_exact" in failed
    assert "serving.fused_attention.hbm_bytes_ratio" in failed
    assert "attribution.floor_ratio.serve_decode_fused" in failed
    ok = {r["key"]: r["status"] for r in doc["rows"]}
    assert ok["attribution.floor_ratio.serve_decode"] == "ok"


def test_runs_clean_against_checked_in_trajectory(capsys):
    """The acceptance check: the archive agrees with itself — the newest
    trajectory point diffed against the trajectory is not a regression."""
    mod = _tool()
    rc = mod.main([os.path.join(REPO, "BENCH_r05.json")])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "bench-diff PASSED" in out
    assert "regression" not in out.split("bench-diff")[0]


def test_regression_and_budget_violations_exit_nonzero(tmp_path, capsys):
    mod = _tool()
    fresh = {
        "parsed": {
            "value": 1000.0,  # ~60% below the trajectory's 2554
            "vs_baseline": 0.4,
        },
        # blown absolute budgets (docs promise <1% / zero false firing)
        "observability": {
            "request_tracing_overhead_pct": 2.5,
            "alerting_overhead_pct": 1.8,
            "alerts_fired_on_healthy_run": 1,
        },
    }
    path = tmp_path / "fresh.json"
    path.write_text(json.dumps(fresh))
    rc = mod.main([str(path), "--json", "-"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    failed = {r["key"] for r in doc["rows"] if r["status"] == "regression"}
    assert "value" in failed
    assert "observability.request_tracing_overhead_pct" in failed
    assert "observability.alerting_overhead_pct" in failed
    assert "observability.alerts_fired_on_healthy_run" in failed
    assert doc["counts"]["regressions"] >= 3


def test_direction_semantics_up_down_and_tolerance():
    mod = _tool()
    ref = {"value": 100.0, "serving": {"ttft_p99_ms": 50.0}}
    spec = [
        {"key": "value", "direction": "up", "tol_pct": 10.0},
        {"key": "serving.ttft_p99_ms", "direction": "down", "tol_pct": 20.0},
    ]
    ok = mod.diff({"value": 91.0, "serving": {"ttft_p99_ms": 59.0}}, ref, spec)
    assert ok["ok"] and ok["counts"]["checked"] == 2
    worse = mod.diff(
        {"value": 89.0, "serving": {"ttft_p99_ms": 61.0}}, ref, spec
    )
    assert not worse["ok"]
    assert [r["status"] for r in worse["rows"]] == ["regression"] * 2


def test_missing_metrics_are_skipped_not_failed(capsys):
    mod = _tool()
    report = mod.diff({"value": 2554.1}, {"value": 2554.1}, mod.DEFAULT_SPEC)
    assert report["ok"]
    assert report["counts"]["skipped"] > 0
    for row in report["rows"]:
        if row["status"] == "skipped":
            assert "why" in row


def test_unreadable_inputs_exit_2(tmp_path, capsys):
    mod = _tool()
    assert mod.main([str(tmp_path / "nope.json")]) == 2
    assert "cannot read" in capsys.readouterr().err
    bad = tmp_path / "fresh.json"
    bad.write_text("{}")
    empty = tmp_path / "emptyrepo"
    empty.mkdir()
    assert mod.main([str(bad), "--repo-root", str(empty)]) == 2
    assert "no trajectory" in capsys.readouterr().err
