"""Pallas flash-attention kernel parity (interpreter mode on the CPU
mesh; the compiled-on-TPU check lives in test_kernels_tpu.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from consensusml_tpu.models.attention import dot_product_attention
from consensusml_tpu.models import flash_attention as fa_mod
from consensusml_tpu.models.flash_attention import flash_attention


@pytest.fixture(autouse=True)
def small_blocks(monkeypatch):
    # interpreter mode is slow: shrink the (TPU-tuned 512) blocks so
    # multi-block paths are exercised at test-sized sequences
    monkeypatch.setattr(fa_mod, "_BQ", 64)
    monkeypatch.setattr(fa_mod, "_BK", 64)


def _qkv(rng, b, s, h, d):
    return tuple(
        jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32) for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("s", [128, 100])  # exact blocks and padded tail
def test_flash_forward_matches_dense(causal, s):
    rng = np.random.default_rng(0)
    q, k, v = _qkv(rng, 2, s, 2, 64)
    want = dot_product_attention(q, k, v, causal=causal, dtype=jnp.float32, impl="dense")
    got = flash_attention(q, k, v, causal=causal, dtype=jnp.float32, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_match_dense(causal):
    rng = np.random.default_rng(1)
    q, k, v = _qkv(rng, 1, 128, 2, 64)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    flash_fn = loss(
        lambda q, k, v: flash_attention(
            q, k, v, causal=causal, dtype=jnp.float32, interpret=True
        )
    )
    dense_fn = loss(
        lambda q, k, v: dot_product_attention(
            q, k, v, causal=causal, dtype=jnp.float32, impl="dense"
        )
    )
    gf = jax.grad(flash_fn, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(dense_fn, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gf, gd):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4,
            err_msg=f"d{name}",
        )


def test_flash_rejects_cross_attention():
    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.normal(size=(1, 64, 2, 64)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, 128, 2, 64)), jnp.float32)
    with pytest.raises(ValueError, match="self-attention"):
        flash_attention(q, k, q, causal=False)


def test_auto_dispatch_never_picks_flash_off_tpu():
    # the CPU test mesh must route long sequences to blockwise, not the
    # TPU kernel
    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.normal(size=(1, 1024, 1, 64)), jnp.bfloat16)
    auto = dot_product_attention(q, q, q, causal=True)
    blk = dot_product_attention(q, q, q, causal=True, impl="blockwise")
    np.testing.assert_array_equal(np.asarray(auto), np.asarray(blk))


def test_explicit_flash_rejects_bias():
    rng = np.random.default_rng(4)
    q = jnp.asarray(rng.normal(size=(1, 64, 1, 64)), jnp.float32)
    bias = jnp.zeros((1, 1, 1, 64), jnp.float32)
    with pytest.raises(ValueError, match="bias"):
        dot_product_attention(q, q, q, bias=bias, impl="flash")


def test_non_dividing_blocks_pad_to_common_multiple(monkeypatch):
    # _BQ=64, _BK=48 at s=100: a _BQ-only pad would drop tail keys
    monkeypatch.setattr(fa_mod, "_BK", 48)
    rng = np.random.default_rng(5)
    q, k, v = _qkv(rng, 1, 100, 1, 64)
    want = dot_product_attention(q, k, v, causal=True, dtype=jnp.float32, impl="dense")
    got = flash_attention(q, k, v, causal=True, dtype=jnp.float32, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


# two combos cover both axes (causal interplay; padded-tail blocks)
# without quadrupling a ~7-15 s interpret-mode parity run
@pytest.mark.parametrize("causal,s", [(False, 128), (True, 100)])
def test_flash_kv_mask_matches_dense_bias(causal, s):
    """Per-key padding mask (the BERT attention_mask form) against the
    dense path's additive-bias formulation, fwd + grads."""
    rng = np.random.default_rng(6)
    q, k, v = _qkv(rng, 2, s, 2, 64)
    # ragged "sequence lengths" incl. one full row: 1=attend, 0=padding
    kv_mask = jnp.asarray(
        np.stack([np.arange(s) < s, np.arange(s) < (3 * s // 5)]), jnp.float32
    )
    bias = jnp.where(kv_mask[:, None, None, :] > 0, 0.0, -1e30)

    def flash_loss(q, k, v):
        o = flash_attention(
            q, k, v, causal=causal, kv_mask=kv_mask, dtype=jnp.float32,
            interpret=True,
        )
        return jnp.sum(o**2), o

    def dense_loss(q, k, v):
        o = dot_product_attention(
            q, k, v, causal=causal, bias=bias, dtype=jnp.float32, impl="dense"
        )
        return jnp.sum(o**2), o

    (_, got), gf = jax.value_and_grad(flash_loss, argnums=(0, 1, 2), has_aux=True)(q, k, v)
    (_, want), gd = jax.value_and_grad(dense_loss, argnums=(0, 1, 2), has_aux=True)(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)
    for name, a, b in zip("qkv", gf, gd):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4,
            err_msg=f"d{name}",
        )


def test_kv_mask_batch_rows_are_independent():
    """The (b // heads) index map must hand each batch its OWN mask row —
    a batch-0-only bug would be invisible to single-batch parity tests."""
    rng = np.random.default_rng(7)
    b, s, h, d = 3, 64, 2, 64
    q, k, v = _qkv(rng, b, s, h, d)
    lens = [64, 40, 17]
    kv_mask = jnp.asarray(
        np.stack([np.arange(s) < n for n in lens]), jnp.float32
    )
    got = flash_attention(
        q, k, v, kv_mask=kv_mask, dtype=jnp.float32, interpret=True
    )
    for i, n in enumerate(lens):
        # each batch row must equal its OWN single-batch masked attention
        want = flash_attention(
            q[i : i + 1], k[i : i + 1], v[i : i + 1],
            kv_mask=kv_mask[i : i + 1], dtype=jnp.float32, interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(got[i]), np.asarray(want[0]), rtol=2e-5, atol=2e-5,
            err_msg=f"batch {i} (len {n})",
        )


def test_dot_product_attention_kv_mask_across_impls():
    rng = np.random.default_rng(8)
    q, k, v = _qkv(rng, 2, 96, 2, 64)
    kv_mask = jnp.asarray(
        np.stack([np.arange(96) < 70, np.arange(96) < 33]), jnp.float32
    )
    dense = dot_product_attention(
        q, k, v, kv_mask=kv_mask, dtype=jnp.float32, impl="dense"
    )
    blk = dot_product_attention(
        q, k, v, kv_mask=kv_mask, dtype=jnp.float32, impl="blockwise"
    )
    np.testing.assert_allclose(
        np.asarray(blk), np.asarray(dense), rtol=2e-5, atol=2e-5
    )
    with pytest.raises(ValueError, match="not both"):
        dot_product_attention(
            q, k, v, kv_mask=kv_mask, bias=jnp.zeros((2, 1, 1, 96))
        )
    with pytest.raises(ValueError, match="kv_mask must be"):
        dot_product_attention(q, k, v, kv_mask=kv_mask[:, :10])
