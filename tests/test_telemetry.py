"""Observability subsystem: span tracer, metrics registry, flight
recorder, and the train.py telemetry surface (docs/observability.md).

``pytest -m telemetry`` runs this tier; everything here is also tier-1
fast (no subprocesses, 3-round smoke at MLP scale).
"""

import json
import os

import jax
import jax.numpy as jnp
import pytest

from consensusml_tpu.obs import (
    FlightRecorder,
    MetricsRegistry,
    SpanTracer,
    get_registry,
    get_tracer,
)

pytestmark = pytest.mark.telemetry


# ---------------------------------------------------------------------------
# span tracer
# ---------------------------------------------------------------------------


def test_span_ring_is_bounded():
    t = SpanTracer(capacity=8)
    for i in range(32):
        with t.span("s", i=i):
            pass
    evs = t.events()
    assert len(evs) == 8
    # oldest dropped: the survivors are the LAST 8
    assert [e["args"]["i"] for e in evs] == list(range(24, 32))


def test_span_nesting_depth_and_duration():
    t = SpanTracer()
    with t.span("outer"):
        with t.span("inner"):
            pass
    inner, outer = t.events()
    assert inner["name"] == "inner" and inner["depth"] == 1
    assert outer["name"] == "outer" and outer["depth"] == 0
    assert outer["dur_us"] >= inner["dur_us"]
    # child's interval is contained in the parent's (how Perfetto nests)
    assert outer["ts_us"] <= inner["ts_us"]
    assert (
        inner["ts_us"] + inner["dur_us"]
        <= outer["ts_us"] + outer["dur_us"] + 1e-3
    )


def test_disabled_tracer_records_nothing():
    t = SpanTracer(enabled=False)
    with t.span("s"):
        pass
    t.instant("i")
    assert t.events() == []


def test_chrome_trace_export_is_valid_trace_event_json(tmp_path):
    t = SpanTracer()
    with t.span("gossip.round", backend="simulated"):
        with t.span("bucket.pack", buckets=3):
            pass
    t.instant("mark")
    path = t.write_chrome_trace(str(tmp_path / "trace.json"))
    doc = json.load(open(path))
    evs = doc["traceEvents"]
    assert evs[0]["ph"] == "M"  # process_name metadata
    by_name = {e["name"]: e for e in evs if e.get("ph") == "X"}
    assert by_name["bucket.pack"]["args"]["buckets"] == 3
    assert by_name["gossip.round"]["dur"] >= by_name["bucket.pack"]["dur"]
    for e in evs:
        if e["ph"] == "X":
            assert {"name", "pid", "tid", "ts", "dur"} <= set(e)
    assert any(e["ph"] == "i" for e in evs)


def test_span_works_inside_jit_tracing():
    t = SpanTracer()

    @jax.jit
    def f(x):
        with t.span("jitted.region"):
            return x * 2

    assert float(f(jnp.float32(3))) == 6.0
    assert [e["name"] for e in t.events()] == ["jitted.region"]
    float(f(jnp.float32(4)))  # cached: no re-trace, no new span
    assert len(t.events()) == 1


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------


def test_counter_gauge_histogram_exposition():
    r = MetricsRegistry()
    r.counter("t_requests_total", "requests").inc(3)
    r.gauge("t_depth").set(2.5)
    h = r.histogram("t_latency_seconds", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = r.to_prometheus()
    assert "# TYPE t_requests_total counter" in text
    assert "t_requests_total 3" in text
    assert "t_depth 2.5" in text
    assert 't_latency_seconds_bucket{le="0.1"} 1' in text
    assert 't_latency_seconds_bucket{le="1"} 2' in text
    assert 't_latency_seconds_bucket{le="+Inf"} 3' in text
    assert "t_latency_seconds_count 3" in text
    assert text.endswith("\n")


def test_counter_rejects_decrease_and_type_conflicts():
    r = MetricsRegistry()
    c = r.counter("t_x_total")
    with pytest.raises(ValueError):
        c.inc(-1)
    assert r.counter("t_x_total") is c  # get-or-create is idempotent
    with pytest.raises(ValueError):
        r.gauge("t_x_total")


def test_prometheus_write_is_atomic_and_snapshot_ring_bounded(tmp_path):
    r = MetricsRegistry(snapshot_keep=4)
    r.gauge("t_g").set(1)
    path = str(tmp_path / "m.prom")
    r.write_prometheus(path)
    assert "t_g 1" in open(path).read()
    assert not [p for p in os.listdir(tmp_path) if ".tmp." in p]
    for i in range(9):
        r.snapshot({"round": i})
    snaps = r.snapshots()
    assert len(snaps) == 4
    assert [s["round"] for s in snaps] == [5, 6, 7, 8]
    assert snaps[-1]["metrics"]["t_g"] == 1.0


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def test_flight_recorder_dump_contains_spans_and_snapshots(tmp_path):
    t = SpanTracer()
    r = MetricsRegistry()
    with t.span("gossip.round"):
        pass
    r.counter("t_rounds_total").inc(7)
    r.snapshot({"round": 6})
    rec = FlightRecorder(str(tmp_path / "fr"), tracer=t, registry=r)
    path = rec.dump("unit-test", detail="boom")
    assert path and os.path.exists(path)
    doc = json.load(open(path))
    assert doc["reason"] == "unit-test"
    assert doc["detail"] == "boom"
    assert [s["name"] for s in doc["spans"]] == ["gossip.round"]
    assert any(
        e.get("name") == "gossip.round" for e in doc["trace_events"]
    )
    assert doc["metric_snapshots"][0]["round"] == 6
    assert doc["metrics_final"]["metrics"]["t_rounds_total"] == 7


def test_flight_recorder_sigterm_dump_chains(tmp_path):
    """The SIGTERM trigger (launcher preemption): the dump lands and the
    PREVIOUS handler still runs. A benign handler is installed first so
    the chained default disposition never kills pytest."""
    import os as _os
    import signal
    import sys
    import time as _time

    t, r = SpanTracer(), MetricsRegistry()
    with t.span("gossip.round"):
        pass
    r.counter("t_rounds_total").inc(2)
    rec = FlightRecorder(str(tmp_path / "fr"), tracer=t, registry=r)
    import threading

    seen = []
    prev_sig = signal.signal(signal.SIGTERM, lambda s, f: seen.append(s))
    prev_hook = sys.excepthook
    prev_thread_hook = threading.excepthook
    try:
        rec.install(sigterm=True)
        _os.kill(_os.getpid(), signal.SIGTERM)
        deadline = _time.monotonic() + 10.0
        while not seen and _time.monotonic() < deadline:
            _time.sleep(0.01)  # signal delivery is between bytecodes
    finally:
        signal.signal(signal.SIGTERM, prev_sig)
        sys.excepthook = prev_hook
        threading.excepthook = prev_thread_hook
    assert seen == [signal.SIGTERM]  # the chained handler ran
    assert rec.last_dump_path and os.path.exists(rec.last_dump_path)
    doc = json.load(open(rec.last_dump_path))
    assert doc["reason"] == "sigterm"
    assert [s["name"] for s in doc["spans"]] == ["gossip.round"]
    assert doc["metrics_final"]["metrics"]["t_rounds_total"] == 2


def test_flight_recorder_excepthook_chains(tmp_path):
    import sys
    import threading

    t, r = SpanTracer(), MetricsRegistry()
    rec = FlightRecorder(str(tmp_path / "fr"), tracer=t, registry=r)
    prev_hook = sys.excepthook
    prev_thread_hook = threading.excepthook
    seen = []
    sys.excepthook = lambda *a: seen.append(a)
    try:
        rec.install(sigterm=False)
        try:
            raise RuntimeError("synthetic crash")
        except RuntimeError:
            sys.excepthook(*sys.exc_info())
    finally:
        sys.excepthook = prev_hook
        threading.excepthook = prev_thread_hook
    assert rec.last_dump_path and os.path.exists(rec.last_dump_path)
    doc = json.load(open(rec.last_dump_path))
    assert doc["reason"] == "unhandled-exception"
    assert "synthetic crash" in doc["detail"]
    assert len(seen) == 1  # the previous hook still ran


# ---------------------------------------------------------------------------
# engine telemetry accessors
# ---------------------------------------------------------------------------


def _tiny_params():
    return {"w": jnp.zeros((256, 64), jnp.float32), "b": jnp.zeros((64,))}


def test_engine_telemetry_exact_and_compressed():
    from consensusml_tpu.compress import topk_int8_compressor
    from consensusml_tpu.consensus import ConsensusEngine, GossipConfig
    from consensusml_tpu.topology import RingTopology

    shapes = jax.eval_shape(_tiny_params)
    exact = ConsensusEngine(GossipConfig(topology=RingTopology(4)))
    t = exact.telemetry(shapes)
    assert t["compression_ratio"] == pytest.approx(1.0)
    assert t["gossip_buckets"] >= 1
    assert t["neighbor_sends_per_round"] == 2  # ring: left + right
    assert t["wire_bytes_per_neighbor"] * 2 == t["wire_bytes_per_round"]

    comp = ConsensusEngine(
        GossipConfig(
            topology=RingTopology(4),
            compressor=topk_int8_compressor(chunk=64, k=4),
            gamma=0.5,
        )
    )
    tc = comp.telemetry(shapes)
    assert tc["compression_ratio"] > 4
    assert tc["wire_bytes_per_round"] < t["wire_bytes_per_round"]

    # gossip_steps multiplies the round's wire but NOT the codec's ratio
    # or the per-send payload
    import dataclasses

    multi = ConsensusEngine(
        dataclasses.replace(comp.config, gossip_steps=2)
    )
    tm = multi.telemetry(shapes)
    assert tm["wire_bytes_per_round"] == 2 * tc["wire_bytes_per_round"]
    assert tm["wire_bytes_per_neighbor"] == tc["wire_bytes_per_neighbor"]
    assert tm["compression_ratio"] == pytest.approx(tc["compression_ratio"])


def test_engine_choco_residual():
    from consensusml_tpu.compress import topk_int8_compressor
    from consensusml_tpu.consensus import ConsensusEngine, GossipConfig
    from consensusml_tpu.topology import RingTopology

    eng = ConsensusEngine(
        GossipConfig(
            topology=RingTopology(4),
            compressor=topk_int8_compressor(chunk=64, k=4),
            gamma=0.5,
        )
    )
    state = eng.init_state(_tiny_params(), world_size=4)
    assert eng.choco_residual(state) == pytest.approx(0.0)
    exact = ConsensusEngine(GossipConfig(topology=RingTopology(4)))
    assert exact.choco_residual(exact.init_state(_tiny_params())) is None


# ---------------------------------------------------------------------------
# MetricsLogger shim (backward-compat layer over the registry)
# ---------------------------------------------------------------------------


def test_metrics_logger_context_manager_closes_and_feeds_registry(tmp_path):
    import io

    from consensusml_tpu.utils import MetricsLogger

    reg = MetricsRegistry()
    path = str(tmp_path / "m.jsonl")
    stream = io.StringIO()
    with MetricsLogger(path, stream=stream, registry=reg) as logger:
        logger.log(0, {"loss": 1.5, "consensus_error": 0.25})
        f = logger._file
    assert f is not None and f.closed  # __exit__ closed the handle
    rec = json.loads(open(path).read().splitlines()[0])
    assert rec["round"] == 0 and rec["loss"] == 1.5
    assert reg.gauge("consensusml_loss").value == 1.5
    assert reg.gauge("consensusml_consensus_error").value == 0.25
    assert "loss=1.5000" in stream.getvalue()


def test_metrics_logger_close_is_exception_safe(tmp_path):
    from consensusml_tpu.utils import MetricsLogger

    path = str(tmp_path / "m.jsonl")
    with pytest.raises(RuntimeError):
        with MetricsLogger(path, registry=MetricsRegistry()) as logger:
            f = logger._file
            raise RuntimeError("mid-run crash")
    assert f.closed


# ---------------------------------------------------------------------------
# tools/xprof_summary.py: host-trace merge + clear missing-path errors
# ---------------------------------------------------------------------------


def test_xprof_summary_missing_dir_clear_error(monkeypatch, capsys):
    import importlib.util
    import sys as _sys

    spec = importlib.util.spec_from_file_location(
        "xprof_summary",
        os.path.join(os.path.dirname(__file__), "..", "tools", "xprof_summary.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setattr(_sys, "argv", ["xprof_summary.py", "/nonexistent/prof"])
    rc = mod.main()
    assert rc == 1
    err = capsys.readouterr().err
    assert "does not exist" in err and "Traceback" not in err


def test_xprof_summary_host_trace_groups_spans(tmp_path):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "xprof_summary",
        os.path.join(os.path.dirname(__file__), "..", "tools", "xprof_summary.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    t = SpanTracer()
    for i in range(3):
        with t.span("train.round", round=i):
            pass
    path = t.write_chrome_trace(str(tmp_path / "trace.json"))
    (row,) = mod.summarize_host_trace(path)
    assert row["span"] == "train.round" and row["count"] == 3
    assert row["total_ms"] >= 0


# ---------------------------------------------------------------------------
# the 3-round CPU smoke: train.py with every sink on (acceptance run)
# ---------------------------------------------------------------------------


def test_train_smoke_writes_prom_and_trace(tmp_path, capsys):
    import train as train_cli

    trace_path = tmp_path / "trace.json"
    prom_path = tmp_path / "metrics.prom"
    tracer = get_tracer()
    was_enabled = tracer.enabled
    try:
        rc = train_cli.main(
            [
                "--config", "mnist_mlp",
                "--device", "cpu",
                "--backend", "simulated",
                "--rounds", "3",
                "--telemetry-every", "2",
                "--trace-events", str(trace_path),
                "--metrics-prom", str(prom_path),
                "--metrics-port", "0",
            ]
        )
    finally:
        tracer.enabled = was_enabled
    assert rc == 0
    # the live /metrics endpoint came up on a free port and was
    # announced (closed again by the CLI's exit stack)
    assert "metrics endpoint: http://127.0.0.1:" in capsys.readouterr().out

    # (a) Perfetto-loadable trace with nested gossip.round -> bucket spans
    doc = json.load(open(trace_path))
    evs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    for e in evs:
        assert {"name", "pid", "tid", "ts", "dur"} <= set(e)
    by_name = {}
    for e in evs:
        by_name.setdefault(e["name"], []).append(e)
    assert len(by_name["train.round"]) == 3
    (g,) = by_name["gossip.round"]  # compile-round engine trace
    (pack,) = by_name["bucket.pack"]
    # nesting: the bucket stage lies inside the gossip round's interval
    assert g["ts"] <= pack["ts"]
    assert pack["ts"] + pack["dur"] <= g["ts"] + g["dur"] + 1e-3
    assert "bucket.unpack" in by_name and "train.inner_loop" in by_name

    # (b) Prometheus textfile with the headline families
    text = open(prom_path).read()
    assert "# TYPE consensusml_round_latency_seconds histogram" in text
    assert "consensusml_round_latency_seconds_count" in text
    assert "# TYPE consensusml_wire_bytes_total counter" in text
    assert "# TYPE consensusml_consensus_distance gauge" in text
    assert "# TYPE consensusml_rounds_total counter" in text
    assert "consensusml_wire_bytes_per_neighbor" in text

    # the registry really accumulated the run's rounds
    reg = get_registry()
    assert reg.counter("consensusml_rounds_total").value >= 3
