"""Model zoo tests: shapes, param counts, and decentralized training smoke
runs for each family (the reference's per-workload coverage, SURVEY.md §4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from consensusml_tpu.consensus import GossipConfig
from consensusml_tpu.data import SyntheticClassification, round_batches
from consensusml_tpu.models import resnet18, resnet50, resnet_init, resnet_loss_fn
from consensusml_tpu.topology import RingTopology
from consensusml_tpu.train import (
    LocalSGDConfig,
    init_stacked_state,
    make_simulated_train_step,
)


def _param_count(params):
    return sum(x.size for x in jax.tree.leaves(params))


def test_resnet50_param_count_and_shapes():
    """Canonical ResNet-50: ~25.6M params, 1000-way logits."""
    model = resnet50(num_classes=1000, stem="imagenet", dtype=jnp.float32)
    variables = model.init(jax.random.key(0), jnp.zeros((1, 224, 224, 3)), train=False)
    n = _param_count(variables["params"])
    assert 25_500_000 < n < 25_700_000, f"param count {n}"
    logits = model.apply(variables, jnp.zeros((2, 224, 224, 3)), train=False)
    assert logits.shape == (2, 1000)
    assert logits.dtype == jnp.float32
    assert "batch_stats" in variables


def test_resnet_cifar_stem_keeps_resolution():
    model = resnet18(num_classes=10, stem="cifar", dtype=jnp.float32)
    variables = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)), train=False)
    logits = model.apply(variables, jnp.zeros((2, 32, 32, 3)), train=False)
    assert logits.shape == (2, 10)


def test_resnet_bn_state_updates_in_train_mode():
    model = resnet18(num_classes=10, stem="cifar", dtype=jnp.float32)
    init = resnet_init(model, input_shape=(1, 32, 32, 3))
    params, state = init(jax.random.key(0))
    loss_fn = resnet_loss_fn(model)
    batch = {
        "image": jnp.ones((4, 32, 32, 3)),
        "label": jnp.zeros((4,), jnp.int32),
    }
    (loss, new_state), grads = jax.value_and_grad(loss_fn, has_aux=True)(
        params, state, batch, jax.random.key(1)
    )
    assert jnp.isfinite(loss)
    # running stats must actually move
    before = jax.tree.leaves(state["batch_stats"])
    after = jax.tree.leaves(new_state["batch_stats"])
    assert any(
        not np.allclose(np.asarray(a), np.asarray(b)) for a, b in zip(before, after)
    )


def test_config2_resnet_ring_training_smoke():
    """BASELINE.json configs[1] at toy scale: a tiny ResNet (same code path
    as resnet50 — bottleneck blocks, BN, CIFAR stem) on a 4-worker ring,
    BN state gossiped with weights; loss falls."""
    from consensusml_tpu.models.resnet import BottleneckBlock, ResNet

    topo = RingTopology(4)
    model = ResNet(
        stage_sizes=[1, 1],
        block=BottleneckBlock,
        num_classes=10,
        width=8,
        stem="cifar",
        dtype=jnp.float32,
    )
    cfg = LocalSGDConfig(
        gossip=GossipConfig(topology=topo),
        optimizer=optax.sgd(5e-2, momentum=0.9),
        h=1,
    )
    data = SyntheticClassification(n=256, image_shape=(16, 16, 3), noise=0.25)
    step = make_simulated_train_step(cfg, resnet_loss_fn(model))
    state = init_stacked_state(
        cfg, resnet_init(model, (1, 16, 16, 3)), jax.random.key(0), 4
    )
    losses = []
    for batch in round_batches(data, 4, h=1, batch=8, rounds=8):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all()
    assert min(losses[-3:]) < losses[0], f"no improvement: {losses[:3]} -> {losses[-3:]}"
    # BN stats were gossiped: all workers share finite stats
    for leaf in jax.tree.leaves(state.model_state):
        assert np.isfinite(np.asarray(leaf)).all()
