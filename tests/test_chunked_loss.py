"""Parity tests: chunked-vocab LM loss vs the dense-logits path.

The chunked path (losses.chunked_vocab_lm_loss) must match dense
masked_lm_loss over the tied head to f32 rounding — values AND
gradients (including the DOUBLE use of the embedding: input lookup +
head), across chunk sizes that do and do not divide the vocab.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from consensusml_tpu.models.gpt2 import GPT2Config, GPT2LM, gpt2_loss_fn
from consensusml_tpu.models.losses import (
    chunked_vocab_lm_loss,
    masked_lm_loss,
)


@pytest.mark.parametrize("chunk", [16, 48, 100, 1000])
def test_functional_parity_values_and_grads(chunk):
    """Standalone: chunked == dense over a raw (hidden, embedding)."""
    rng = np.random.default_rng(0)
    n, h, v = 24, 32, 100  # chunk=48 does not divide v; 1000 > v
    hidden = jnp.asarray(rng.normal(size=(n, h)), jnp.float32)
    emb = jnp.asarray(rng.normal(size=(v, h)) * 0.3, jnp.float32)
    labels = jnp.asarray(rng.integers(0, v, size=(n,)), jnp.int32)
    mask = jnp.asarray(rng.random(n) > 0.3, jnp.float32)

    def dense(hidden, emb):
        return masked_lm_loss(hidden @ emb.T, labels, mask)

    def chunked(hidden, emb):
        return chunked_vocab_lm_loss(hidden, emb, labels, mask, chunk=chunk)

    ld, gd = jax.value_and_grad(dense, argnums=(0, 1))(hidden, emb)
    lc, gc = jax.value_and_grad(chunked, argnums=(0, 1))(hidden, emb)
    np.testing.assert_allclose(float(lc), float(ld), rtol=1e-5)
    for a, b in zip(gc, gd):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4
        )


def test_gpt2_loss_fn_parity():
    """End-to-end through gpt2_loss_fn: loss_vocab_chunk>0 matches the
    dense config on identical params, including the wte gradient that
    flows through BOTH the input lookup and the in-loss head. f32 model
    dtype: in bf16 the two paths accumulate the head matmul in different
    chunk orders, so only f32 isolates the MATH parity (a loose bf16
    loss-value check rides below)."""
    kw = dict(
        vocab_size=96, hidden=64, layers=2, heads=4, max_len=32, dropout=0.0,
        dtype=jnp.float32,
    )
    m_dense = GPT2LM(config=GPT2Config(**kw))
    m_chunk = GPT2LM(config=GPT2Config(loss_vocab_chunk=40, **kw))
    ids = jnp.asarray(
        np.random.default_rng(1).integers(0, 96, size=(2, 16)), jnp.int32
    )
    params = m_dense.init(jax.random.key(0), ids)["params"]
    batch = {"input_ids": ids}
    rng = jax.random.key(1)

    def run(model):
        fn = gpt2_loss_fn(model)
        def scalar(p):
            return fn(p, {}, batch, rng)[0]
        return jax.value_and_grad(scalar)(params)

    ld, gd = run(m_dense)
    lc, gc = run(m_chunk)
    np.testing.assert_allclose(float(lc), float(ld), rtol=2e-5)
    flat_d = jax.tree_util.tree_leaves_with_path(gd)
    flat_c = dict(
        (jax.tree_util.keystr(k), v)
        for k, v in jax.tree_util.tree_leaves_with_path(gc)
    )
    for k, vd in flat_d:
        vc = flat_c[jax.tree_util.keystr(k)]
        np.testing.assert_allclose(
            np.asarray(vc), np.asarray(vd), atol=2e-4, rtol=2e-3
        )


def test_gpt2_loss_fn_bf16_loss_close():
    """bf16 model dtype (the production config): losses agree to bf16
    rounding even though grad accumulation orders differ."""
    kw = dict(
        vocab_size=96, hidden=64, layers=2, heads=4, max_len=32, dropout=0.0
    )
    m_dense = GPT2LM(config=GPT2Config(**kw))
    m_chunk = GPT2LM(config=GPT2Config(loss_vocab_chunk=40, **kw))
    ids = jnp.asarray(
        np.random.default_rng(3).integers(0, 96, size=(2, 16)), jnp.int32
    )
    params = m_dense.init(jax.random.key(0), ids)["params"]
    batch = {"input_ids": ids}
    rng = jax.random.key(1)
    ld = float(gpt2_loss_fn(m_dense)(params, {}, batch, rng)[0])
    lc = float(gpt2_loss_fn(m_chunk)(params, {}, batch, rng)[0])
    np.testing.assert_allclose(lc, ld, rtol=2e-2)


def test_loss_mask_respected():
    rng = np.random.default_rng(2)
    hidden = jnp.asarray(rng.normal(size=(8, 16)), jnp.float32)
    emb = jnp.asarray(rng.normal(size=(50, 16)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 50, size=(8,)), jnp.int32)
    m1 = jnp.asarray([1, 1, 0, 0, 0, 0, 0, 0], jnp.float32)
    full = chunked_vocab_lm_loss(hidden[:2], emb, labels[:2], m1[:2], chunk=20)
    masked = chunked_vocab_lm_loss(hidden, emb, labels, m1, chunk=20)
    np.testing.assert_allclose(float(full), float(masked), rtol=1e-6)


def test_llama_loss_fn_parity():
    """Llama's UNTIED head (lm_head kernel (H, V), passed transposed)
    matches the dense path on identical params, f32 dtype."""
    from consensusml_tpu.models.llama import LlamaConfig, LlamaLM, llama_loss_fn

    kw = dict(
        vocab_size=90, hidden=48, layers=2, heads=4, kv_heads=2,
        mlp_dim=96, max_len=32, dtype=jnp.float32,
    )
    m_dense = LlamaLM(config=LlamaConfig(**kw))
    m_chunk = LlamaLM(config=LlamaConfig(loss_vocab_chunk=32, **kw))
    ids = jnp.asarray(
        np.random.default_rng(5).integers(0, 90, size=(2, 12)), jnp.int32
    )
    params = m_dense.init(jax.random.key(0), ids)["params"]
    batch = {"input_ids": ids}
    rng = jax.random.key(1)

    def run(model):
        fn = llama_loss_fn(model)
        return jax.value_and_grad(lambda p: fn(p, {}, batch, rng)[0])(params)

    ld, gd = run(m_dense)
    lc, gc = run(m_chunk)
    np.testing.assert_allclose(float(lc), float(ld), rtol=2e-5)
    for (ka, va), (kb, vb) in zip(
        jax.tree_util.tree_leaves_with_path(gc),
        jax.tree_util.tree_leaves_with_path(gd),
    ):
        assert jax.tree_util.keystr(ka) == jax.tree_util.keystr(kb)
        np.testing.assert_allclose(
            np.asarray(va), np.asarray(vb), atol=2e-4, rtol=2e-3,
            err_msg=jax.tree_util.keystr(ka),
        )
