"""Checkpoint-cadence regression (ISSUE 6 satellite): the
``--checkpoint-dir`` + ``--checkpoint-every`` background write used to
crash inside orbax on any state holding typed PRNG keys — device_get
hands the background thread a numpy-backed key array ArrayHandler cannot
walk, and orbax cannot serialize typed key arrays at all. The fix stores
keys as raw uint32 key data (utils/checkpoint.py ``_unwrap_keys``) and
re-wraps them from the restore template, so these tests pin the whole
cadence -> resume -> elastic-resume loop in tier-1.
"""

import os

import jax
import jax.numpy as jnp
import pytest


def test_async_saver_typed_key_state_roundtrip(tmp_path):
    """Unit-level regression: a state with typed PRNG-key leaves (what
    every TrainState.rng holds) survives the async write + restore."""
    from consensusml_tpu.utils import AsyncSaver, restore_state

    state = {
        "w": jnp.arange(8.0).reshape(2, 4),
        "rng": jnp.stack([jax.random.key(i) for i in range(4)]),
    }
    saver = AsyncSaver()
    saver.submit(str(tmp_path / "ck"), state, step=3)
    saver.wait()
    assert saver.last_path is not None
    like = {
        "w": jnp.zeros((2, 4)),
        "rng": jnp.stack([jax.random.key(0)] * 4),
    }
    got = restore_state(saver.last_path, like)
    assert (got["w"] == state["w"]).all()
    assert jax.dtypes.issubdtype(got["rng"].dtype, jax.dtypes.prng_key)
    assert (
        jax.random.key_data(got["rng"]) == jax.random.key_data(state["rng"])
    ).all()
    # the restored keys are USABLE, not just structurally right
    jax.random.uniform(got["rng"][0])


def test_checkpoint_cadence_writes_and_resume(tmp_path):
    """train.py --checkpoint-every writes mid-run checkpoints that a
    later --resume (same world) restores; previously crashed on the
    first cadence boundary."""
    import train as train_cli

    ck = tmp_path / "ck"
    rc = train_cli.main(
        [
            "--config", "mnist_mlp",
            "--device", "cpu",
            "--backend", "simulated",
            "--rounds", "4",
            "--checkpoint-dir", str(ck),
            "--checkpoint-every", "2",
        ]
    )
    assert rc == 0
    assert os.path.isdir(ck / "step_2") and os.path.isdir(ck / "step_4")
    # the mid-run checkpoint is complete (meta landed after the tree)
    from consensusml_tpu.utils import checkpoint_round, checkpoint_world_size

    assert checkpoint_world_size(str(ck / "step_2")) == 4
    assert checkpoint_round(str(ck / "step_2")) == 2

    rc = train_cli.main(
        [
            "--config", "mnist_mlp",
            "--device", "cpu",
            "--backend", "simulated",
            "--rounds", "2",
            "--resume", str(ck / "step_4"),
        ]
    )
    assert rc == 0


@pytest.mark.slow
def test_checkpoint_cadence_elastic_resume(tmp_path):
    """The cadence checkpoint feeds the elastic path too: resume at a
    different world size (ROADMAP item 4's churn loop rides this)."""
    import train as train_cli

    ck = tmp_path / "ck"
    assert train_cli.main(
        [
            "--config", "mnist_mlp",
            "--device", "cpu",
            "--backend", "simulated",
            "--rounds", "2",
            "--checkpoint-dir", str(ck),
            "--checkpoint-every", "2",
        ]
    ) == 0
    assert train_cli.main(
        [
            "--config", "mnist_mlp",
            "--device", "cpu",
            "--backend", "simulated",
            "--rounds", "1",
            "--resume", str(ck / "step_2"),
            "--workers", "6",
        ]
    ) == 0
