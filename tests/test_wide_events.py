"""Wide-event request accounting (ISSUE 17): the per-request cost join,
tenant attribution, rollups, and per-tenant SLOs.

Acceptance anchors:

- **The join balances** — every terminal request emits exactly ONE wide
  event whose cost is the ledger's own rows (``decode_ticks`` × the
  per-step row + one prefill-bucket row per admission), whose timings
  are the request trace's own events, and whose block-seconds are the
  pool's hold-time integral; per-tenant rollups re-derive the engine's
  own totals.
- **Tenant SLOs ride PR 14 unchanged** — the engine's labeled
  ``consensusml_tenant_ttft_seconds`` children give every tenant its own
  burn-rate rule via the alert engine's labeled-children matching, and
  a burst on one tenant fires ONLY that tenant's alert.
- **E2E** — multi-tenant socket loadgen → ServeServer → paged engine
  over a 10-block pool (structural recompute-preemption) with a
  mid-traffic hot swap: every wide event joins its trace by trace_id,
  the ``/events``/``/tenants`` endpoints serve the log, and the cluster
  aggregate + obs_report carry the per-tenant table (absent, not
  broken, on pre-wide-event snapshot directories).
"""

import importlib.util
import json
import os
import sys
import time
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from consensusml_tpu.obs import (
    AlertRule,
    ClusterWriter,
    FlightRecorder,
    MetricsHistory,
    MetricsRegistry,
    MetricsServer,
    RequestTraceRegistry,
    SloSpec,
    SpanTracer,
    TraceContext,
    aggregate,
    get_registry,
    get_request_registry,
)
from consensusml_tpu.obs import events as events_mod
from consensusml_tpu.obs import metrics as metrics_mod
from consensusml_tpu.obs import requests as requests_mod
from consensusml_tpu.obs.alerts import AlertEngine
from consensusml_tpu.obs.events import (
    WORST_TTFT_KEEP,
    WideEventLog,
    get_wide_event_log,
    peek_wide_event_log,
    reset_wide_event_log,
    sanitize_tenant,
)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

pytestmark = [pytest.mark.telemetry, pytest.mark.serving]


def _tiny_gpt2(max_len=32):
    from consensusml_tpu.models.gpt2 import GPT2Config, GPT2LM

    return GPT2LM(
        config=GPT2Config(
            vocab_size=64, hidden=32, layers=2, heads=2, max_len=max_len,
            dropout=0.0,
        )
    )


def _init(model, seq=8, seed=0):
    return model.init(
        jax.random.key(seed), jnp.zeros((1, seq), jnp.int32)
    )["params"]


def _fresh_obs(monkeypatch):
    """Fresh process-wide registries + wide-event log: earlier in-process
    serving runs must not leak events into these assertions."""
    monkeypatch.setattr(metrics_mod, "_GLOBAL", MetricsRegistry())
    monkeypatch.setattr(requests_mod, "_GLOBAL", RequestTraceRegistry())
    reset_wide_event_log()


# ---------------------------------------------------------------------------
# tenant label + log semantics
# ---------------------------------------------------------------------------


def test_sanitize_tenant_boundary():
    assert sanitize_tenant(None) == "default"
    assert sanitize_tenant("") == "default"
    assert sanitize_tenant("batch-eval.v2_A") == "batch-eval.v2_A"
    # untrusted line-JSON input: charset enforced, once, at the boundary
    assert sanitize_tenant("a b/c{d}") == "a_b_c_d_"
    assert sanitize_tenant(42) == "42"
    assert len(sanitize_tenant("x" * 200)) == 64


def test_log_ring_bound_jsonl_sink_and_filters(tmp_path):
    path = str(tmp_path / "events.jsonl")
    log = WideEventLog(capacity=4, jsonl_path=path)
    for i in range(10):
        log.emit({"tenant": "a" if i % 2 else "b", "i": i,
                  "bad": float("nan")})
    assert len(log) == 4  # ring bound: oldest dropped
    assert log.emitted_total == 10
    assert [e["i"] for e in log.events()] == [6, 7, 8, 9]  # newest-last
    assert [e["i"] for e in log.events(2)] == [8, 9]
    assert [e["i"] for e in log.events(tenant="a")] == [7, 9]
    assert log.tenants() == ["a", "b"]
    # every emitted event is stamped and JSON-safe
    for e in log.events():
        assert e["bad"] is None and e["time_s"] > 0
    # the sink holds the FULL history, one strict-JSON line per event
    log.close()
    with open(path) as f:
        lines = [json.loads(x) for x in f]
    assert len(lines) == 10
    assert all(ln.get("bad") is None for ln in lines)
    with pytest.raises(ValueError):
        WideEventLog(capacity=0)


def test_rollup_aggregates_and_worst_ttft_cap():
    log = WideEventLog()
    for i in range(12):
        log.emit({
            "tenant": "t0", "prompt_len": 4, "tokens_out": 8,
            "tflops": 0.5, "hbm_bytes": 2e9, "block_seconds": 0.25,
            "decode_ticks": 8, "defer_ticks": 1, "preemptions": i % 2,
            "ttft_s": 0.01 * (i + 1), "request_id": f"r{i}",
            "trace_id": f"tr{i}",
        })
    log.emit({"tenant": "t1", "prompt_len": 2, "tokens_out": 0,
              "ttft_s": None})
    roll = log.rollup()
    t0 = roll["t0"]
    assert t0["requests"] == 12
    assert t0["tokens_in"] == 48 and t0["tokens_out"] == 96
    assert t0["tflops"] == pytest.approx(6.0)
    assert t0["hbm_gbytes"] == pytest.approx(24.0)
    assert t0["block_seconds"] == pytest.approx(3.0)
    assert t0["decode_ticks"] == 96 and t0["defer_ticks"] == 12
    assert t0["preemptions"] == 6
    # worst-first, capped like the exemplar rings
    worst = t0["worst_ttft"]
    assert len(worst) == WORST_TTFT_KEEP
    assert worst[0]["request_id"] == "r11"
    assert [w["ttft_s"] for w in worst] == sorted(
        (w["ttft_s"] for w in worst), reverse=True
    )
    # a zero-token terminal (no first token) contributes no TTFT sample
    assert roll["t1"]["worst_ttft"] == []
    snap = log.snapshot(last_n=3)
    assert snap["emitted_total"] == 13 and snap["retained"] == 13
    assert len(snap["events_recent"]) == 3
    assert set(snap["tenants"]) == {"t0", "t1"}


def test_singleton_arm_peek_reset(monkeypatch, tmp_path):
    reset_wide_event_log()
    assert peek_wide_event_log() is None  # a dump must not create one
    path = str(tmp_path / "sink.jsonl")
    monkeypatch.setenv("CONSENSUSML_WIDE_EVENTS_JSONL", path)
    log = get_wide_event_log()
    assert peek_wide_event_log() is log
    assert get_wide_event_log() is log
    log.emit({"tenant": "env"})
    assert os.path.exists(path)  # env-configured durable sink
    reset_wide_event_log()
    assert peek_wide_event_log() is None


# ---------------------------------------------------------------------------
# pool block-seconds: the hold-time integral, deterministic clock
# ---------------------------------------------------------------------------


def test_block_pool_block_seconds_deterministic_clock():
    from consensusml_tpu.serve import pool as P

    now = [0.0]
    pool = P.BlockPool(
        num_slots=2, max_len=32, block_size=8, clock=lambda: now[0]
    )
    pool.alloc(0, 2)  # 2 blocks held from t=0
    now[0] = 1.0
    assert pool.block_seconds(0) == pytest.approx(2.0)
    pool.extend(0, 1)  # 3 blocks from t=1
    now[0] = 3.0
    assert pool.block_seconds(0) == pytest.approx(2.0 + 3 * 2.0)
    pool.shrink(0, 1)  # 1 block from t=3
    now[0] = 4.0
    assert pool.block_seconds(0) == pytest.approx(8.0 + 1.0)
    # a second slot integrates independently
    pool.alloc(1, 1)
    now[0] = 6.0
    assert pool.block_seconds(1) == pytest.approx(2.0)
    assert pool.block_seconds(0) == pytest.approx(8.0 + 3.0)
    pool.release(0)
    assert pool.block_seconds(0) == 0.0  # settled out with the release
    assert pool.block_seconds(1) == pytest.approx(2.0)


# ---------------------------------------------------------------------------
# engine emission: one event per terminal, ledger-joined
# ---------------------------------------------------------------------------


def test_engine_emits_joined_events_and_tenant_series(monkeypatch):
    from consensusml_tpu.obs import CostLedger
    from consensusml_tpu.serve import Engine, ServeConfig

    _fresh_obs(monkeypatch)
    reg = get_registry()
    model = _tiny_gpt2()
    params = _init(model)
    led = CostLedger(registry=MetricsRegistry())
    with Engine(
        model, params, ServeConfig(num_slots=4, max_len=32, max_new_tokens=8)
    ) as eng:
        eng.warmup()
        eng.register_costs(led)
        handles = [
            eng.submit(
                [1 + i % 30] * (3 + i % 7),
                tenant=("alpha", "beta")[i % 2],
                trace=TraceContext(f"we-{i}"),
            )
            for i in range(6)
        ]
        results = [h.result(timeout=300) for h in handles]
        stats = eng.stats()
    log = peek_wide_event_log()
    assert log is not None and log.emitted_total == 6
    events = log.events()
    decode_row = led.row("serve.decode")
    by_rid = {e["request_id"]: e for e in events}
    for i, (h, r) in enumerate(zip(handles, results)):
        ev = by_rid[f"we-{i}/0"]
        assert ev["trace_id"] == f"we-{i}"
        assert ev["tenant"] == ("alpha", "beta")[i % 2]
        assert ev["finish_reason"] == r.finish_reason
        assert ev["tokens_out"] == len(r.tokens)
        assert ev["prompt_len"] == 3 + i % 7
        assert ev["ttft_s"] == pytest.approx(r.ttft_s, abs=1e-5)
        assert ev["latency_s"] == pytest.approx(r.latency_s, abs=1e-5)
        assert ev["generation"] == r.generation
        # the joined trace timeline: every stage offset present, ordered
        st = ev["stages_us"]
        for stage in ("submit", "admission", "prefill", "decode",
                      "complete"):
            assert stage in st, (ev["request_id"], st)
        assert st["submit"] <= st["admission"] <= st["prefill"]
        assert st["prefill"] <= st["decode"] <= st["complete"]
        # the cost join is the ledger's OWN rows, exactly
        assert ev["cost_joined"] is True
        expected_flops = ev["decode_ticks"] * decode_row.flops + sum(
            led.row(f"serve.prefill.b{b}").flops
            for b in ev["prefill_buckets"]
        )
        assert ev["flops"] == pytest.approx(expected_flops)
        assert ev["tflops"] == pytest.approx(expected_flops / 1e12)
        assert ev["hbm_bytes"] > 0
        assert 0 < ev["decode_ticks"] <= len(r.tokens)
    # stats carries prompt-side totals; the rollup re-derives both
    assert stats["tokens_in"] == sum(3 + i % 7 for i in range(6))
    roll = log.rollup()
    assert sum(a["tokens_in"] for a in roll.values()) == stats["tokens_in"]
    assert sum(a["tokens_out"] for a in roll.values()) == stats["tokens_out"]
    assert sum(a["requests"] for a in roll.values()) == 6
    # the labeled per-tenant families landed in the process registry
    m = reg.snapshot()["metrics"]
    assert m['consensusml_tenant_requests_total{tenant="alpha"}'] == 3.0
    assert m['consensusml_tenant_requests_total{tenant="beta"}'] == 3.0
    assert m['consensusml_tenant_tokens_total{tenant="alpha"}'] == sum(
        len(r.tokens) for i, r in enumerate(results) if i % 2 == 0
    )
    assert m['consensusml_tenant_tflops_total{tenant="alpha"}'] > 0
    assert 'consensusml_tenant_ttft_seconds{tenant="beta"}' in m


def test_engine_without_ledger_still_emits_unjoined(monkeypatch):
    from consensusml_tpu.serve import Engine, ServeConfig

    _fresh_obs(monkeypatch)
    model = _tiny_gpt2()
    with Engine(
        model, _init(model),
        ServeConfig(num_slots=2, max_len=32, max_new_tokens=4),
    ) as eng:
        eng.warmup()
        eng.submit([1, 2, 3], tenant="solo").result(timeout=300)
    (ev,) = peek_wide_event_log().events()
    assert ev["cost_joined"] is False
    assert ev["flops"] == 0.0 and ev["tflops"] == 0.0
    assert ev["tenant"] == "solo" and ev["tokens_out"] == 4


# ---------------------------------------------------------------------------
# surfacing: /events + /tenants, flight dump, cluster aggregate
# ---------------------------------------------------------------------------


def _get_json(url):
    return json.loads(urllib.request.urlopen(url).read().decode())


def test_httpd_events_endpoints(monkeypatch):
    _fresh_obs(monkeypatch)
    reg = MetricsRegistry()
    with MetricsServer(registry=reg) as ms:
        base = f"http://{ms.address[0]}:{ms.address[1]}"
        # un-armed: enabled=False, never created as a scrape side effect
        doc = _get_json(base + "/events")
        assert doc == {"enabled": False, "events": [],
                       "emitted_total": 0}
        assert peek_wide_event_log() is None
        assert _get_json(base + "/tenants") == {
            "enabled": False, "tenants": {},
        }
        log = get_wide_event_log()  # the producer arms it
        for i in range(5):
            log.emit({"tenant": "a" if i < 3 else "b", "i": i,
                      "tokens_out": 2})
        doc = _get_json(base + "/events?n=2")
        assert doc["enabled"] is True and doc["emitted_total"] == 5
        assert [e["i"] for e in doc["events"]] == [3, 4]
        doc = _get_json(base + "/events?tenant=a")
        assert [e["i"] for e in doc["events"]] == [0, 1, 2]
        doc = _get_json(base + "/tenants")
        assert doc["tenants"]["a"]["requests"] == 3
        assert doc["tenants"]["b"]["tokens_out"] == 4
        with pytest.raises(urllib.error.HTTPError) as err:
            _get_json(base + "/events?n=zap")
        assert err.value.code == 400


def test_flight_dump_embeds_wide_events(tmp_path, monkeypatch):
    _fresh_obs(monkeypatch)
    # a custom-registry recorder must NOT embed the global plane
    rec = FlightRecorder(str(tmp_path / "iso"), registry=MetricsRegistry())
    get_wide_event_log().emit({"tenant": "t", "tokens_out": 1})
    doc = json.load(open(rec.dump("unit-test")))
    assert "wide_events" not in doc
    # a global-registry recorder peeks the armed log at dump time
    rec2 = FlightRecorder(str(tmp_path / "glob"))
    doc = json.load(open(rec2.dump("unit-test")))
    we = doc["wide_events"]
    assert we["emitted_total"] == 1
    assert we["tenants"]["t"]["requests"] == 1
    # explicit wiring wins over the peek
    other = WideEventLog()
    other.emit({"tenant": "x"})
    other.emit({"tenant": "x"})
    rec3 = FlightRecorder(str(tmp_path / "wired"), events=other)
    doc = json.load(open(rec3.dump("unit-test")))
    assert doc["wide_events"]["emitted_total"] == 2


def test_cluster_aggregate_merges_tenants(tmp_path, monkeypatch):
    _fresh_obs(monkeypatch)
    log = get_wide_event_log()
    # rank 0 sees tenants a+b, rank 1 (a disjoint engine's log) only a
    for i in range(4):
        log.emit({"tenant": "a" if i % 2 else "b", "prompt_len": 2,
                  "tokens_out": 3, "tflops": 0.1, "block_seconds": 0.5,
                  "ttft_s": 0.01 * (i + 1), "request_id": f"r0-{i}"})
    # default-registry writers peek the armed global log at write time
    ClusterWriter(str(tmp_path), rank=0).write()
    other = WideEventLog()
    other.emit({"tenant": "a", "prompt_len": 8, "tokens_out": 1,
                "tflops": 0.4, "block_seconds": 1.0, "ttft_s": 0.5,
                "request_id": "r1-0"})
    ClusterWriter(str(tmp_path), rank=1, events=other).write()
    doc = aggregate(str(tmp_path))
    tn = doc["tenants"]
    assert tn["ranks_reporting"] == 2 and tn["events_total"] == 5
    a = tn["tenants"]["a"]
    assert a["requests"] == 3  # 2 from rank 0 + 1 from rank 1
    assert a["tokens_in"] == 2 * 2 + 8
    assert a["tflops"] == pytest.approx(0.2 + 0.4)
    assert a["block_seconds"] == pytest.approx(0.5 * 2 + 1.0)
    # merged worst-TTFT re-sorted across ranks, worst first
    assert a["worst_ttft"][0]["request_id"] == "r1-0"
    assert tn["tenants"]["b"]["requests"] == 2


def test_cluster_aggregate_tenants_absent_on_old_snapshots(tmp_path,
                                                           monkeypatch):
    """Pre-wide-event snapshot directories aggregate and render with the
    tenant plane marked absent — never broken."""
    _fresh_obs(monkeypatch)
    ClusterWriter(str(tmp_path), rank=0, registry=get_registry()).write()
    doc = aggregate(str(tmp_path))
    assert doc["tenants"] is None
    mod = _obs_report()
    text = mod.render_text(doc)
    assert "tenants: absent (no snapshot carries wide-event accounting)" \
        in text


def _obs_report():
    spec = importlib.util.spec_from_file_location(
        "obs_report",
        os.path.join(os.path.dirname(__file__), "..", "tools",
                     "obs_report.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# loadgen: weighted tenant mix
# ---------------------------------------------------------------------------


def test_parse_tenant_weights():
    from tools.loadgen import parse_tenant_weights

    assert parse_tenant_weights(None) is None
    assert parse_tenant_weights("a=3,b=1") == [("a", 3.0), ("b", 1.0)]
    # bare names weight 1; labels sanitized at the boundary
    assert parse_tenant_weights("batch, bad name=2") == [
        ("batch", 1.0), ("bad_name", 2.0),
    ]
    with pytest.raises(ValueError):
        parse_tenant_weights("a=0")
    with pytest.raises(ValueError):
        parse_tenant_weights(",")
    with pytest.raises(ValueError):
        parse_tenant_weights("a=x")


# ---------------------------------------------------------------------------
# e2e acceptance: multi-tenant loadgen -> server -> paged engine
# ---------------------------------------------------------------------------


class _StubWatcher:
    """One staged swap, engine-thread protocol only (take/reject/stop).
    ``gate`` defers the take until the ENGINE observes the condition —
    evaluated inside its own tick, so a gated swap always lands while
    the condition still holds (no submit-thread-vs-tick race)."""

    def __init__(self, staged, gate=None):
        self._staged = [staged]
        self._gate = gate

    def take(self):
        if self._gate is not None and not self._gate():
            return None
        return self._staged.pop() if self._staged else None

    def reject(self, staged=None):  # pragma: no cover - mismatch path
        raise AssertionError("same-tree swap must not be rejected")

    def stop(self):
        pass


def test_e2e_multitenant_join_rollup_and_tenant_slo(tmp_path, monkeypatch):
    """The acceptance round-trip: a weighted two-tenant socket loadgen
    drives a ServeServer over a 10-block paged pool (structural
    recompute-preemption) with a mid-traffic hot swap. Every wide event
    joins its completed trace by trace_id; the rollup re-derives the
    engine totals; the endpoints serve the log; and a TTFT burst on ONE
    tenant fires only that tenant's burn-rate alert through the stock
    labeled-children matching."""
    from consensusml_tpu.serve import Engine, ServeConfig, ServeServer
    from consensusml_tpu.serve.pool.hotswap import StagedSwap
    from tools.loadgen import _socket_submit, parse_tenant_weights, \
        run_loadgen

    _fresh_obs(monkeypatch)
    rt = get_request_registry()
    reg = get_registry()
    model = _tiny_gpt2()
    params = _init(model)
    # 10 blocks cannot hold 4 full streams -> recompute-preemption fires
    engine = Engine(
        model, params,
        ServeConfig(
            num_slots=4, max_len=32, kv_impl="paged", block_size=8,
            num_blocks=10, max_new_tokens=8,
        ),
    )
    server = ServeServer(engine, metrics_port=0)
    try:
        engine.warmup()
        host, port = server.address
        report = run_loadgen(
            _socket_submit(host, port),
            n_requests=10, rate_rps=300.0, prompt_lens=(4, 16),
            vocab=64, max_new_tokens=8, seed=3,
            tenants=parse_tenant_weights("batch=3,interactive=1"),
        )
        assert report["errors"] == 0 and report["completed"] == 10
        # the client-side report attributes per tenant, echoing the
        # server-resolved label
        tn = report["tenants"]
        assert set(tn) == {"batch", "interactive"}
        assert sum(t["completed"] for t in tn.values()) == 10
        for t in tn.values():
            if t["completed"]:
                assert t["ttft_p99_ms"] > 0

        # induce a drain-free hot swap under live tenant streams: the
        # gated watcher lands the swap only on a tick where all 3
        # streams are live, so the structural preemption (evictions
        # > 0) is guaranteed even when warm jit caches let decode
        # outrun this thread
        engine._watcher = _StubWatcher(
            StagedSwap(generation=2, params=engine._params, meta={}),
            gate=lambda: engine._table.num_active >= 3,
        )
        long_handles = [
            engine.submit([7, 8, 9, 10], max_new_tokens=16,
                          trace=TraceContext(f"swp-{i}"), tenant="batch")
            for i in range(3)
        ]
        results = [h.result(timeout=120) for h in long_handles]
        assert engine.generation == 2
        assert all(r.tenant == "batch" for r in results)
        stats = engine.stats()
        assert stats["evictions"] > 0

        # live endpoints on the serving side
        mhost, mport = server.metrics_address
        doc = _get_json(f"http://{mhost}:{mport}/events?n=100")
        assert doc["enabled"] is True and doc["emitted_total"] == 13
        doc = _get_json(f"http://{mhost}:{mport}/tenants")
        assert set(doc["tenants"]) <= {"batch", "interactive"}
    finally:
        server.shutdown(drain=True)

    log = peek_wide_event_log()
    events = log.events()
    assert len(events) == 13  # one per terminal, rejected emit nothing

    # ---- every wide event joins its completed trace by trace_id ---------
    done = {tr.request_id: tr for tr in rt.completed()}
    for ev in events:
        tr = done[ev["request_id"]]
        assert ev["trace_id"] == tr.trace_id
        assert ev["tenant"] == tr.tenant
        assert ev["decode_ticks"] == tr.decode_ticks
        assert ev["defer_ticks"] == tr.defer_ticks
        assert ev["preemptions"] == tr.preemptions
        assert ev["kv_impl"] == "paged"
        st = ev["stages_us"]
        assert st["submit"] <= st["admission"] <= st["complete"]
    # the induced pressure landed in the events, not just the stats
    assert sum(e["preemptions"] for e in events) > 0
    preempted = [e for e in events if e["preemptions"]]
    for ev in preempted:  # re-admission re-prefills: bucket per admit
        assert len(ev["prefill_buckets"]) >= 2
    assert any(e["generation"] == 2 for e in events)
    assert all(e["block_seconds"] > 0 for e in events)

    # ---- the rollup re-derives the engine totals ------------------------
    roll = log.rollup()
    assert sum(a["requests"] for a in roll.values()) == 13
    assert sum(a["tokens_out"] for a in roll.values()) == stats["tokens_out"]
    assert sum(a["tokens_in"] for a in roll.values()) == stats["tokens_in"]
    assert sum(a["preemptions"] for a in roll.values()) == sum(
        e["preemptions"] for e in events
    )

    # ---- per-tenant burn-rate SLO through the stock alert engine --------
    # the engine's labeled TTFT children exist for every seen tenant;
    # ONE rule over the family covers them all (PR 14 labeled-children
    # matching), and a burst on "interactive" pages only "interactive"
    fam = "consensusml_tenant_ttft_seconds"
    hist = MetricsHistory(reg, keep=16)
    rule = AlertRule(
        "tenant-ttft-burn", fam, kind="burn_rate", severity="page",
        slo=SloSpec(fam, threshold_s=0.1, objective=0.95),
        fast_window_s=60.0, slow_window_s=300.0, burn_factor=4.0,
    )
    eng = AlertEngine(hist, rules=[rule], registry=reg,
                      tracer=SpanTracer(), quiet=True)
    hist.record(now=0.0)
    assert eng.evaluate(now=0.0) == []
    burst = engine._tenant_metrics("interactive")["ttft"]
    calm = engine._tenant_metrics("batch")["ttft"]
    for _ in range(15):
        calm.observe(0.01)  # healthy tenant: all under threshold
        burst.observe(0.01)
    for _ in range(5):
        burst.observe(0.4)  # the burst: 5/20 over -> burn 5x > factor 4
    hist.record(now=60.0)
    firing = eng.evaluate(now=60.0)
    assert len(firing) == 1
    assert firing[0]["series"] == fam + '{tenant="interactive"}'

    # ---- fleet merge + report render ------------------------------------
    obs_dir = tmp_path / "obs"
    ClusterWriter(str(obs_dir), rank=0, role="serve").write(
        extra={"request_traces": rt.snapshot()}
    )
    doc = aggregate(str(obs_dir))
    agg = doc["tenants"]
    assert agg["events_total"] == 13
    assert sum(
        a["requests"] for a in agg["tenants"].values()
    ) == 13
    mod = _obs_report()
    text = mod.render_text(doc)
    assert "tenant accounting" in text
    for name in roll:
        assert name in text
    assert mod.main([str(obs_dir)]) == 0
