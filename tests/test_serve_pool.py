"""Paged KV pool, disaggregated stages, drain-free hot swap (ISSUE 8).

Three pinned properties:

- **Paged-attention parity** — decode through the block pool is
  bit-exact against the PR 5 per-slot cache path stage by stage, and the
  greedy streams it serves match the full causal forward token for
  token, for both causal-LM families.
- **Free-list invariants** — no double-alloc, no double-free, no leak:
  free ∪ owned partitions the physical blocks across admit/extend/
  release cycles, randomized churn, and real engine admit/evict/swap
  traffic (block exhaustion preempts by recompute and the stream still
  completes, tokens intact).
- **Drain-free hot swap** — the e2e acceptance: train 2 rounds →
  export → serve concurrent streams → export a NEW generation
  mid-traffic → the engine flips params between decode steps with zero
  dropped streams and zero recompiles after warmup.
"""

import os
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from consensusml_tpu import configs
from consensusml_tpu.serve import Engine, ServeConfig, load_engine
from consensusml_tpu.serve import decode as D
from consensusml_tpu.serve import pool as P
from consensusml_tpu.serve.export import (
    bump_generation,
    export_serving,
    serving_meta,
)
from consensusml_tpu.serve.pool.hotswap import GenerationWatcher

pytestmark = pytest.mark.serving


def _tiny_gpt2():
    from consensusml_tpu.models.gpt2 import GPT2Config, GPT2LM

    return GPT2LM(
        config=GPT2Config(
            vocab_size=64, hidden=32, layers=2, heads=2, max_len=32, dropout=0.0
        )
    )


def _tiny_llama():
    from consensusml_tpu.models.llama import llama_tiny

    return llama_tiny()


def _init(model, seq=8, seed=0):
    return model.init(jax.random.key(seed), jnp.zeros((1, seq), jnp.int32))["params"]


# ---------------------------------------------------------------------------
# Block pool accounting
# ---------------------------------------------------------------------------


def test_block_pool_alloc_extend_release_invariants():
    pool = P.BlockPool(num_slots=4, max_len=32, block_size=8)  # 16 + trash
    assert pool.usable_blocks == 16
    assert pool.free_blocks == 16
    got = pool.alloc(0, 2)
    assert len(got) == 2 and P.TRASH_BLOCK not in got
    assert pool.owned(0) == got
    assert pool.free_blocks == 14
    more = pool.extend(0, 1)
    assert pool.owned(0) == got + more
    pool.check()
    freed = pool.release(0)
    assert sorted(freed) == sorted(got + more)
    assert pool.free_blocks == 16
    # the released slot's table row points at trash again
    assert np.all(np.asarray(pool.device_table())[0] == P.TRASH_BLOCK)
    pool.check()


def test_block_pool_rejects_misuse():
    pool = P.BlockPool(num_slots=2, max_len=32, block_size=8)
    with pytest.raises(ValueError, match="divide"):
        P.BlockPool(num_slots=2, max_len=30, block_size=8)
    with pytest.raises(ValueError, match="cannot hold"):
        P.BlockPool(num_slots=2, max_len=32, block_size=8, num_blocks=3)
    pool.alloc(0, 2)
    with pytest.raises(RuntimeError, match="double-alloc"):
        pool.alloc(0, 1)
    with pytest.raises(ValueError, match="blocks_per_slot"):
        pool.extend(0, 4)
    with pytest.raises(RuntimeError, match="owns nothing"):
        pool.extend(1, 1)
    pool.release(0)
    with pytest.raises(RuntimeError, match="double-free"):
        pool.release(0)
    # exhaustion raises NoFreeBlocks, never hands out the trash block
    pool.alloc(0, 4)
    pool.alloc(1, 4)
    with pytest.raises(ValueError, match="blocks_per_slot"):
        pool.extend(1, 1)
    pool2 = P.BlockPool(num_slots=2, max_len=32, block_size=8, num_blocks=5)
    pool2.alloc(0, 4)
    with pytest.raises(P.NoFreeBlocks):
        pool2.alloc(1, 1)
    pool2.check()


def test_block_pool_randomized_churn_never_leaks():
    rng = np.random.default_rng(0)
    pool = P.BlockPool(num_slots=8, max_len=64, block_size=8, num_blocks=25)
    live: set[int] = set()
    for _ in range(500):
        if live and rng.random() < 0.4:
            s = int(rng.choice(sorted(live)))
            live.remove(s)
            pool.release(s)
        else:
            free_slots = [s for s in range(8) if s not in live]
            if not free_slots:
                continue
            s = int(rng.choice(free_slots))
            want = int(rng.integers(1, 5))
            try:
                pool.alloc(s, want)
                live.add(s)
            except P.NoFreeBlocks:
                pass
        if live and rng.random() < 0.3:
            s = int(rng.choice(sorted(live)))
            if len(pool.owned(s)) < pool.blocks_per_slot:
                try:
                    pool.extend(s, 1)
                except P.NoFreeBlocks:
                    pass
        pool.check()  # free ∪ owned partitions the blocks, every step
    assert pool.used_blocks == sum(len(pool.owned(s)) for s in live)


def test_blocks_for_tokens():
    assert P.blocks_for_tokens(1, 8) == 1
    assert P.blocks_for_tokens(8, 8) == 1
    assert P.blocks_for_tokens(9, 8) == 2
    assert P.blocks_for_tokens(64, 8) == 8


# ---------------------------------------------------------------------------
# Paged-attention parity
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("family", ["gpt2", "llama"])
def test_paged_stages_bitexact_vs_slot_path(family):
    """Stage-level parity: paged prefill + paged decode produce the SAME
    tokens and the same gathered KV view as the per-slot path, bit for
    bit. gather_paged_kv reassembles (S, max_len, H, D) in the exact
    per-slot layout, so the attention reduction order is identical."""
    model = _tiny_gpt2() if family == "gpt2" else _tiny_llama()
    vocab = model.config.vocab_size
    params = _init(model)
    dm = D.DecodeModel.wrap(model)
    slots, max_len, bs = 2, 32, 8
    prompt = jax.random.randint(jax.random.key(3), (1, 6), 0, vocab)
    bucket = 8  # block-aligned prompt bucket
    ids = np.zeros((1, bucket), np.int32)
    ids[0, :6] = np.asarray(prompt)

    greedy1 = (jnp.float32(0.0), jnp.float32(1.0), jnp.uint32(0))
    greedy = (
        jnp.zeros((slots,), jnp.float32),
        jnp.ones((slots,), jnp.float32),
        jnp.zeros((slots,), jnp.uint32),
    )
    # slot path (PR 5)
    cache = D.init_cache(dm, slots, max_len)
    slot_prefill = D.make_prefill_fn(dm)
    slot_decode = D.make_decode_fn(dm)
    tok_s, logits_s, cache = slot_prefill(
        params, cache, jnp.asarray(ids), jnp.int32(6), jnp.int32(0),
        *greedy1,
    )

    # paged path (pool)
    pool = P.BlockPool(slots, max_len, bs)
    pages = P.init_pages(dm, pool.num_blocks, bs)
    pool.alloc(0, P.blocks_for_tokens(6 + 1, bs))
    paged_prefill = P.make_paged_prefill_fn(dm)
    paged_decode = P.make_paged_decode_fn(dm)
    tok_p, logits_p, pages = paged_prefill(
        params, pages, jnp.asarray(ids), jnp.int32(6),
        jnp.asarray(pool.block_row(0, bucket // bs)),
        *greedy1,
    )
    assert int(tok_s) == int(tok_p)
    np.testing.assert_array_equal(np.asarray(logits_s), np.asarray(logits_p))

    # decode steps cross a block boundary (pos 6..11 crosses at 8)
    toks_s = toks_p = None
    tok_sc, tok_pc = tok_s, tok_p
    pos = 6
    for step in range(6):
        if (pos // bs) >= len(pool.owned(0)):
            pool.extend(0, 1)
        tokens_s = jnp.zeros((slots,), jnp.int32).at[0].set(tok_sc)
        positions = jnp.zeros((slots,), jnp.int32).at[0].set(pos)
        out_s, cache = slot_decode(
            params, cache, tokens_s, positions, *greedy
        )
        tokens_p = jnp.zeros((slots,), jnp.int32).at[0].set(tok_pc)
        out_p, pages = paged_decode(
            params, pages, pool.device_table(), tokens_p, positions,
            *greedy,
        )
        toks_s, toks_p = int(out_s[0]), int(out_p[0])
        assert toks_s == toks_p, f"divergence at decode step {step}"
        tok_sc, tok_pc = toks_s, toks_p
        pos += 1

    # gathered paged view == slot cache rows over the live prefix
    from consensusml_tpu.models.attention import gather_paged_kv

    for layer in range(dm.layers):
        kg, vg = gather_paged_kv(
            pages[layer]["k"], pages[layer]["v"], pool.device_table()
        )
        np.testing.assert_array_equal(
            np.asarray(kg[0, :pos]), np.asarray(cache[layer]["k"][0, :pos])
        )
        np.testing.assert_array_equal(
            np.asarray(vg[0, :pos]), np.asarray(cache[layer]["v"][0, :pos])
        )


@pytest.mark.parametrize(
    # ~60s/family on this box; gpt2 keeps the paged-vs-slot-vs-full parity
    # axis in the fast tier, llama rides the slow tier (its paged path is
    # still exercised fast by the fused-vs-gather stream parity test).
    "family",
    ["gpt2", pytest.param("llama", marks=pytest.mark.slow)],
)
def test_paged_engine_matches_slot_engine_and_full_forward(family):
    """Engine-level parity: the SAME prompts greedily decoded through the
    paged engine, the per-slot engine, and a full-causal-forward loop
    produce identical token streams."""
    model = _tiny_gpt2() if family == "gpt2" else _tiny_llama()
    vocab = model.config.vocab_size
    params = _init(model)
    rng = np.random.default_rng(7)
    prompts = [rng.integers(0, vocab - 1, size=n).tolist() for n in (2, 5, 9, 13)]
    max_new = 6

    def serve(cfg):
        with Engine(model, params, cfg) as eng:
            eng.warmup()
            handles = [eng.submit(p, max_new) for p in prompts]
            return [h.result(timeout=120).tokens for h in handles]

    paged = serve(ServeConfig(num_slots=4, max_len=32, kv_impl="paged"))
    slot = serve(ServeConfig(num_slots=4, max_len=32, kv_impl="slot"))
    assert paged == slot

    # full causal forward, greedy: the reference with no cache at all.
    # The cached path's reduction order differs from the full forward's
    # (PR 5 pinned their logits at atol=1e-4, not bitwise), so a served
    # token must be the full forward's argmax up to that float noise —
    # near-ties may break either way, a wrong token never passes
    for p, toks in zip(prompts, paged):
        ids = list(p)
        for t in range(max_new):
            logits = np.asarray(
                model.apply(
                    {"params": params},
                    jnp.asarray([ids], jnp.int32),
                    deterministic=True,
                )[0, -1]
            )
            assert logits[toks[t]] >= logits.max() - 1e-4, (
                f"prompt len {len(p)}, step {t}: served token "
                f"{toks[t]} is not the full forward's argmax"
            )
            ids.append(toks[t])  # follow the served stream


# ---------------------------------------------------------------------------
# Engine admit/evict/swap traffic over the pool
# ---------------------------------------------------------------------------


def test_engine_eviction_recompute_completes_all_streams():
    """A pool too small for the offered concurrency preempts streams by
    recompute (blocks free, the stream re-enqueues) — every stream still
    completes with its full token count, token-identical to an engine
    that never evicts, and the free list balances afterwards."""
    model = _tiny_gpt2()
    params = _init(model)
    prompts = [
        np.random.default_rng(i).integers(0, 63, size=4 + 3 * i).tolist()
        for i in range(4)
    ]
    # 16 generated tokens per stream: peak demand is 14 blocks (3+3+4+4)
    # against the tight pool's 9 usable, so eviction pressure is
    # STRUCTURAL — it cannot be raced away by one stream finishing
    # before another is admitted on a slow, loaded box
    max_new = 16

    def serve(num_blocks):
        cfg = ServeConfig(
            num_slots=4, max_len=32, kv_impl="paged", block_size=8,
            num_blocks=num_blocks,
        )
        with Engine(model, params, cfg) as eng:
            eng.warmup()
            handles = [eng.submit(p, max_new) for p in prompts]
            results = [h.result(timeout=120) for h in handles]
            stats = eng.stats()
            eng._pool.check()  # invariants hold after live traffic
            assert stats["pool"]["free_blocks"] == stats["pool"]["usable_blocks"]
        return results, stats

    # 9 usable blocks cannot hold 4 streams growing toward ~26 tokens
    tight, tight_stats = serve(num_blocks=10)
    roomy, roomy_stats = serve(num_blocks=0)  # auto: never evicts
    assert roomy_stats["evictions"] == 0
    assert tight_stats["evictions"] > 0
    assert [r.tokens for r in tight] == [r.tokens for r in roomy]
    assert all(len(r.tokens) == max_new for r in tight)
    assert all(r.finish_reason == "max_tokens" for r in tight)


def test_admission_scheduler_budget():
    s = P.AdmissionScheduler(prefill_budget=32)
    s.start_tick()
    assert s.try_admit(64)  # first admission of a tick always fits
    assert not s.try_admit(8)  # budget already spent
    s.start_tick()
    assert s.try_admit(16)
    assert s.try_admit(16)
    assert not s.try_admit(8)
    s.start_tick()
    assert s.try_admit(8)
    with pytest.raises(ValueError):
        P.AdmissionScheduler(prefill_budget=0)


# ---------------------------------------------------------------------------
# Generations: export counter + watcher protocol
# ---------------------------------------------------------------------------


def _export_tiny_artifact(tmp_path, seed=0, **kw):
    from consensusml_tpu.train import init_stacked_state

    bundle = configs.build("gpt2_topk", "smoke")
    state = init_stacked_state(
        bundle.cfg, bundle.init_params, jax.random.key(seed), bundle.world_size
    )
    return export_serving(
        str(tmp_path / "art"), state, config_name="gpt2_topk", round=0, **kw
    )


def test_export_generation_monotonic(tmp_path):
    art = _export_tiny_artifact(tmp_path)
    assert serving_meta(art)["generation"] == 1
    _export_tiny_artifact(tmp_path, seed=1)  # same dir: re-export bumps
    assert serving_meta(art)["generation"] == 2
    assert bump_generation(art) == 3
    assert serving_meta(art)["generation"] == 3
    with pytest.raises(ValueError, match="generation"):
        _export_tiny_artifact(tmp_path, generation=0)


def test_watcher_stages_new_generations_and_rejects_backwards(tmp_path):
    """Protocol unit test with an injected loader (no orbax restore):
    stage iff the generation strictly advances; reading a REGRESSED meta
    counts a rejection and never stages."""
    art = _export_tiny_artifact(tmp_path)
    loads = []

    def loader(path):
        loads.append(path)
        return serving_meta(path), {"w": jnp.zeros((2,))}, {}

    w = GenerationWatcher.__new__(GenerationWatcher)  # no thread: poll by hand
    import threading

    from consensusml_tpu.obs import get_registry

    w._lock = threading.Lock()  # first: the generation property locks
    w.path, w.poll_s, w.generation = art, 999.0, 1
    w.stage_draft = False
    w._loader, w._staged = loader, None
    w._rejected_gen, w._flip_rejected = None, None
    reg = get_registry()
    w._m_staged = reg.counter("test_pool_w_staged", "t")
    w._m_rejected = reg.counter("test_pool_w_rejected", "t")
    w._m_load = reg.histogram("test_pool_w_load", "t")

    assert not w.poll_once()  # generation 1 == current: nothing to do
    assert loads == [] and w.take() is None
    bump_generation(art)
    assert w.poll_once()  # 2 > 1: loads + stages
    assert loads == [art]
    sw = w.take()
    assert sw.generation == 2 and w.take() is None
    # a stale artifact (generation moved BACKWARDS) is rejected unloaded
    meta = serving_meta(art)
    meta["generation"] = 1
    from consensusml_tpu.serve.export import _write_meta

    before = w._m_rejected.value
    _write_meta(art, meta)
    assert not w.poll_once()
    assert loads == [art]  # no second load
    assert w._m_rejected.value == before + 1
    # the SAME stale meta polled again does not ramp the counter — one
    # regression event counts once, not once per poll
    assert not w.poll_once()
    assert w._m_rejected.value == before + 1

    # engine-side flip rejection rolls the accepted mark back: the same
    # bad artifact is not restaged, but a REWRITE at the same generation
    # (a corrected re-export) is
    meta["generation"] = 3
    _write_meta(art, meta)
    assert w.poll_once()
    sw = w.take()
    assert sw.generation == 3
    w.reject(sw)
    assert w.generation == 2
    assert not w.poll_once()  # same (gen, mtime): skipped, no reload
    assert loads == [art, art]
    _write_meta(art, meta)  # corrected artifact, same generation
    os.utime(
        os.path.join(art, "serve_meta.json"), (time.time(), time.time() + 1)
    )
    assert w.poll_once()  # new mtime: staged again
    assert w.take().generation == 3 and w.generation == 3


# ---------------------------------------------------------------------------
# E2E acceptance: drain-free hot swap mid-traffic
# ---------------------------------------------------------------------------


def test_e2e_hot_swap_mid_traffic(tmp_path):
    """Train 2 rounds → export → serve concurrent streams → export a NEW
    generation mid-traffic → the engine flips between decode steps:
    zero dropped streams, zero recompiles after warmup."""
    import train as train_cli

    from consensusml_tpu.train import init_stacked_state

    art = str(tmp_path / "serving")
    rc = train_cli.main(
        [
            "--config", "gpt2_topk", "--device", "cpu", "--backend", "simulated",
            "--workers", "2", "--rounds", "2", "--log-every", "1",
            "--export-serving", art,
        ]
    )
    assert rc == 0
    assert serving_meta(art)["generation"] == 1

    bundle = configs.build("gpt2_topk", "smoke")
    engine = load_engine(
        art, ServeConfig(num_slots=4, max_len=32, max_new_tokens=24)
    )
    assert engine.generation == 1
    try:
        warm = engine.warmup()
        engine.watch(art, poll_s=0.02)
        rng = np.random.default_rng(5)
        results = []
        swapped_mid_wave = False
        for wave in range(6):
            gen_at_submit = engine.generation
            handles = [
                engine.submit(rng.integers(0, 63, size=n).tolist(), 24)
                for n in (3, 5, 7, 8)
            ]
            if wave == 0:
                # a REAL new artifact (fresh weights, same tree) lands
                # under the live engine — generation auto-bumps to 2.
                # Wave 0 was submitted BEFORE this export, so any wave-0
                # result finishing under generation 2 straddled the flip.
                assert gen_at_submit == 1
                state = init_stacked_state(
                    bundle.cfg, bundle.init_params, jax.random.key(99),
                    bundle.world_size,
                )
                export_serving(art, state, config_name="gpt2_topk", round=0)
                assert serving_meta(art)["generation"] == 2
            wave_results = [h.result(timeout=120) for h in handles]
            results.extend(wave_results)
            gens = {r.generation for r in wave_results}
            if any(r.generation > gen_at_submit for r in wave_results) or (
                engine.generation == 2 and 1 in gens
            ):
                # streams submitted under generation 1 finished under 2
                # (flip landed while they were resident), or finished
                # under 1 with the engine already on 2: the swap was LIVE
                swapped_mid_wave = True
            if engine.generation == 2 and wave >= 1:
                break
        # zero dropped streams: every stream ran to its token cap
        assert all(len(r.tokens) == 24 for r in results)
        assert all(r.finish_reason == "max_tokens" for r in results)
        assert engine.generation == 2, "the staged generation never flipped"
        stats = engine.stats()
        assert stats["swaps"] == 1
        assert swapped_mid_wave, "no stream was in flight across the flip"
        # zero recompiles across the swap: the new tree is byte-shape
        # identical, so the staged params hit the SAME executables
        after = engine.compile_counts()
        assert (after["prefill"], after["decode"]) == (
            warm["prefill"], warm["decode"],
        ), "hot swap recompiled a serving stage"
    finally:
        engine.shutdown()


def test_swap_rejects_mismatched_tree(tmp_path):
    """A staged tree whose leaves do not match the live tree (different
    arch exported over the artifact dir) is rejected at flip time — the
    engine keeps serving the old generation instead of recompiling."""
    model = _tiny_gpt2()
    params = _init(model)
    with Engine(model, params, ServeConfig(num_slots=2, max_len=32)) as eng:
        eng.warmup()
        from consensusml_tpu.serve.pool.hotswap import StagedSwap

        class FakeWatcher:
            def __init__(self):
                self.rejections = 0

            def take(self):
                return StagedSwap(5, {"totally": jnp.zeros((3,))}, {})

            def reject(self, staged=None):
                self.rejections += 1

            def stop(self):
                pass

        eng._watcher = FakeWatcher()
        h = eng.submit([1, 2, 3], 4)
        assert len(h.result(timeout=60).tokens) == 4
        assert eng._watcher.rejections >= 1
        assert eng.generation == 0  # never flipped
