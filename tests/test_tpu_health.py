"""TPU-health preflight and hang-guard tests (VERDICT r3 items 1/6/7).

A wedged TPU tunnel — this box's observed failure mode, where a process's
first ``jax.devices()`` blocks forever — is FAKED via the probe's
``TPU_HEALTH_CMD`` hook (a child that sleeps past the timeout), so the
hang paths are testable with no TPU and no real wedge."""

import json
import os
import subprocess
import sys

from consensusml_tpu.utils.tpu_health import probe

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

FAKE_TPU = (
    "print('TPU_HEALTH ' + __import__('json').dumps("
    "{'platform': 'tpu', 'n_devices': 4, 'device_kind': 'fake-v4'}))"
)
FAKE_CPU = (
    "print('TPU_HEALTH ' + __import__('json').dumps("
    "{'platform': 'cpu', 'n_devices': 8, 'device_kind': 'host'}))"
)
FAKE_HANG = "import time; time.sleep(600)"
FAKE_CRASH = "import sys; sys.stderr.write('boom'); sys.exit(3)"


def _bench_module():
    """Import bench.py (repo root, not a package) as a module."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(REPO, "bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _bench_env(tmp_path, **extra):
    """Env for bench.py subprocess tests: BENCH_DETAIL_PATH is redirected
    so a suite run can never clobber the repo's real BENCH_DETAIL.json
    round record."""
    return {
        **os.environ,
        "BENCH_DETAIL_PATH": str(tmp_path / "detail.json"),
        **extra,
    }


def _final_and_detail(stdout: str):
    """Split bench.py stdout into (final compact record, full detail).

    The driver parses the LAST line; section detail rides an earlier
    ``BENCH_DETAIL`` line (see bench.py FINAL_LINE_LIMIT rationale)."""
    limit = _bench_module().FINAL_LINE_LIMIT
    final_line = [l for l in stdout.splitlines() if l.startswith("{")][-1]
    assert len(final_line.encode()) <= limit, len(final_line.encode())
    detail_line = [
        l for l in stdout.splitlines() if l.startswith("BENCH_DETAIL ")
    ][-1]
    return json.loads(final_line), json.loads(detail_line[len("BENCH_DETAIL "):])


def test_probe_alive_tpu(monkeypatch):
    monkeypatch.setenv("TPU_HEALTH_CMD", FAKE_TPU)
    r = probe(timeout=60)
    assert r["alive"] and r["tpu"]
    assert r["platform"] == "tpu" and r["device_kind"] == "fake-v4"


def test_probe_alive_cpu_is_not_tpu(monkeypatch):
    monkeypatch.setenv("TPU_HEALTH_CMD", FAKE_CPU)
    r = probe(timeout=60)
    assert r["alive"] and not r["tpu"]
    assert r["platform"] == "cpu"


def test_probe_wedged_tunnel_times_out(monkeypatch):
    monkeypatch.setenv("TPU_HEALTH_CMD", FAKE_HANG)
    r = probe(timeout=1.5)
    assert not r["alive"] and not r["tpu"]
    assert "hanging" in r["reason"]
    assert r["elapsed_s"] < 30  # the caller never hangs


def test_probe_crashed_child(monkeypatch):
    monkeypatch.setenv("TPU_HEALTH_CMD", FAKE_CRASH)
    r = probe(timeout=60)
    assert not r["alive"]
    assert "rc=3" in r["reason"] and "boom" in r["reason"]


def test_cli_exit_codes():
    base = {**os.environ}
    for cmd, extra_env, rc in [
        (FAKE_TPU, {}, 0),
        (FAKE_CPU, {}, 1),
        (FAKE_HANG, {"TPU_HEALTH_TIMEOUT": "1"}, 2),
    ]:
        r = subprocess.run(
            [sys.executable, "tools/tpu_health.py"],
            cwd=REPO,
            capture_output=True,
            text=True,
            timeout=120,
            env={**base, "TPU_HEALTH_CMD": cmd, **extra_env},
        )
        assert r.returncode == rc, (cmd, r.stdout, r.stderr)
        out = json.loads(r.stdout.strip().splitlines()[-1])
        assert out["alive"] == (rc != 2)


def test_train_device_tpu_wedged_gives_clean_error():
    """train.py --device tpu on a wedged tunnel exits rc=2 fast with a
    diagnostic instead of hanging in jax.default_backend() forever
    (VERDICT r3 item 6)."""
    r = subprocess.run(
        [sys.executable, "train.py", "--config", "mnist_mlp", "--device", "tpu"],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
        env={**os.environ, "TPU_HEALTH_CMD": FAKE_HANG, "TPU_HEALTH_TIMEOUT": "1"},
    )
    assert r.returncode == 2, (r.stdout, r.stderr)
    assert "probe failed" in r.stderr and "hanging" in r.stderr


def test_train_device_tpu_cpu_only_gives_clean_error():
    r = subprocess.run(
        [sys.executable, "train.py", "--config", "mnist_mlp", "--device", "tpu"],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
        env={**os.environ, "TPU_HEALTH_CMD": FAKE_CPU},
    )
    assert r.returncode == 2, (r.stdout, r.stderr)
    assert "no TPU reachable" in r.stderr


def test_bench_emits_headline_json_when_budget_exhausted(tmp_path):
    """bench.py's one driver-parsed JSON line must land even when the
    global budget leaves no room for any section (VERDICT r3 item 1):
    every section is skipped, value is 0, and the note says why."""
    r = subprocess.run(
        [sys.executable, "bench.py"],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=300,
        env=_bench_env(
            tmp_path,
            BENCH_DEVICE="cpu",  # skips the TPU preflight
            BENCH_TOTAL_BUDGET="10",  # below the per-section floor
        ),
    )
    assert r.returncode == 0, r.stderr[-2000:]
    out, detail = _final_and_detail(r.stdout)
    assert out["unit"] == "imgs/sec/chip" and out["value"] == 0.0
    assert out["vs_baseline"] == 0.0
    assert "budget exhausted" in json.dumps(detail)
    assert detail["preflight"]["skipped"].startswith("BENCH_DEVICE")


def test_bench_wedged_preflight_skips_tpu_sections(tmp_path):
    """With a wedged tunnel the preflight fails fast and bench.py still
    emits the headline line: TPU sections are skipped with an honest
    note, CPU sections are attempted (and here budget-skipped)."""
    r = subprocess.run(
        [sys.executable, "bench.py"],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=300,
        env=_bench_env(
            tmp_path,
            TPU_HEALTH_CMD=FAKE_HANG,
            BENCH_PREFLIGHT_TIMEOUT="2",
            BENCH_TOTAL_BUDGET="40",  # preflight fits, sections don't
        ),
    )
    assert r.returncode == 0, r.stderr[-2000:]
    out, detail = _final_and_detail(r.stdout)
    assert out["value"] == 0.0
    assert "preflight" in detail and detail["preflight"]["alive"] is False
    assert "TPU sections skipped" in out["note"]
    assert "fed_input" not in detail  # never scheduled without a tunnel


def test_bench_sigterm_lands_partial_json(tmp_path):
    """The driver's timeout delivers SIGTERM before SIGKILL; bench.py
    must use that window to print the partial headline line (round 3's
    rc=124/empty-tail failure mode)."""
    import signal
    import time

    proc = subprocess.Popen(
        [sys.executable, "bench.py"],
        cwd=REPO,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env=_bench_env(
            tmp_path,
            BENCH_DEVICE="cpu",
            BENCH_TOTAL_BUDGET="3000",  # roomy: sections would run
        ),
    )
    time.sleep(5)  # inside the first (slow) section's child
    proc.send_signal(signal.SIGTERM)
    out, err = proc.communicate(timeout=60)
    assert proc.returncode == 0, err[-2000:]
    parsed, _ = _final_and_detail(out)
    assert parsed["unit"] == "imgs/sec/chip"
    assert "signal 15" in parsed["note"]


def test_bench_final_line_capped_worst_case():
    """The driver's tail window is ~2000 bytes; round 4's record died when
    the one JSON line outgrew it. build_final_line must cap the line at
    800 bytes for ANY note — including one bigger than the window itself —
    while never dropping the numeric fields."""
    bench = _bench_module()

    worst_note = (
        'a "quoted" note with escapes \\ and unicode é ' * 200
    )  # ~9 KB pre-escaping, expands further when JSON-escaped
    payload = {
        "metric": "imgs/sec/chip (ResNet-50 consensus-SGD, bf16 224px)",
        "value": 2536.13,
        "unit": "imgs/sec/chip",
        "vs_baseline": 1.0144,
        "elapsed_s": 2512.7,
        "note": worst_note,
    }
    line = bench.build_final_line(payload)
    assert len(line.encode("utf-8")) <= bench.FINAL_LINE_LIMIT, len(line.encode("utf-8"))
    out = json.loads(line)
    assert out["value"] == 2536.13 and out["vs_baseline"] == 1.0144
    assert out["unit"] == "imgs/sec/chip" and out["elapsed_s"] == 2512.7
    assert out["note"].endswith("...") and len(out["note"]) > 0

    # empty and short notes pass through untouched
    for note in ("", "short note"):
        line = bench.build_final_line({**payload, "note": note})
        assert json.loads(line)["note"] == note
        assert len(line.encode()) <= bench.FINAL_LINE_LIMIT


def test_bench_final_line_capped_even_without_note_to_trim():
    """With the note exhausted, optional fields drop (in declared order)
    until the line fits; "value" survives every cut. A pathological
    payload that STILL overflows is byte-truncated — an over-window line
    is lost entirely, a clipped one at least lands its head."""
    bench = _bench_module()

    huge_metric = "m" * 2000  # no note to trim: the metric itself overflows
    payload = {
        "metric": huge_metric,
        "value": 2536.13,
        "unit": "imgs/sec/chip",
        "vs_baseline": 1.0144,
        "elapsed_s": 2512.7,
        "note": "",
    }
    line = bench.build_final_line(payload)
    assert len(line.encode("utf-8")) <= bench.FINAL_LINE_LIMIT
    out = json.loads(line)  # still valid JSON: the overflow field dropped
    assert out["value"] == 2536.13
    assert "metric" not in out

    # un-droppable overflow (value itself too wide for a 16-byte limit):
    # byte-truncation is the last resort — never a >limit line
    line = bench.build_final_line({"value": 10.0 / 3.0, "x": "y" * 900}, limit=16)
    assert len(line.encode("utf-8")) <= 16
