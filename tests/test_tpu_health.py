"""TPU-health preflight and hang-guard tests (VERDICT r3 items 1/6/7).

A wedged TPU tunnel — this box's observed failure mode, where a process's
first ``jax.devices()`` blocks forever — is FAKED via the probe's
``TPU_HEALTH_CMD`` hook (a child that sleeps past the timeout), so the
hang paths are testable with no TPU and no real wedge."""

import json
import os
import subprocess
import sys

from consensusml_tpu.utils.tpu_health import probe

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

FAKE_TPU = (
    "print('TPU_HEALTH ' + __import__('json').dumps("
    "{'platform': 'tpu', 'n_devices': 4, 'device_kind': 'fake-v4'}))"
)
FAKE_CPU = (
    "print('TPU_HEALTH ' + __import__('json').dumps("
    "{'platform': 'cpu', 'n_devices': 8, 'device_kind': 'host'}))"
)
FAKE_HANG = "import time; time.sleep(600)"
FAKE_CRASH = "import sys; sys.stderr.write('boom'); sys.exit(3)"


def test_probe_alive_tpu(monkeypatch):
    monkeypatch.setenv("TPU_HEALTH_CMD", FAKE_TPU)
    r = probe(timeout=60)
    assert r["alive"] and r["tpu"]
    assert r["platform"] == "tpu" and r["device_kind"] == "fake-v4"


def test_probe_alive_cpu_is_not_tpu(monkeypatch):
    monkeypatch.setenv("TPU_HEALTH_CMD", FAKE_CPU)
    r = probe(timeout=60)
    assert r["alive"] and not r["tpu"]
    assert r["platform"] == "cpu"


def test_probe_wedged_tunnel_times_out(monkeypatch):
    monkeypatch.setenv("TPU_HEALTH_CMD", FAKE_HANG)
    r = probe(timeout=1.5)
    assert not r["alive"] and not r["tpu"]
    assert "hanging" in r["reason"]
    assert r["elapsed_s"] < 30  # the caller never hangs


def test_probe_crashed_child(monkeypatch):
    monkeypatch.setenv("TPU_HEALTH_CMD", FAKE_CRASH)
    r = probe(timeout=60)
    assert not r["alive"]
    assert "rc=3" in r["reason"] and "boom" in r["reason"]


def test_cli_exit_codes():
    base = {**os.environ}
    for cmd, extra_env, rc in [
        (FAKE_TPU, {}, 0),
        (FAKE_CPU, {}, 1),
        (FAKE_HANG, {"TPU_HEALTH_TIMEOUT": "1"}, 2),
    ]:
        r = subprocess.run(
            [sys.executable, "tools/tpu_health.py"],
            cwd=REPO,
            capture_output=True,
            text=True,
            timeout=120,
            env={**base, "TPU_HEALTH_CMD": cmd, **extra_env},
        )
        assert r.returncode == rc, (cmd, r.stdout, r.stderr)
        out = json.loads(r.stdout.strip().splitlines()[-1])
        assert out["alive"] == (rc != 2)


def test_train_device_tpu_wedged_gives_clean_error():
    """train.py --device tpu on a wedged tunnel exits rc=2 fast with a
    diagnostic instead of hanging in jax.default_backend() forever
    (VERDICT r3 item 6)."""
    r = subprocess.run(
        [sys.executable, "train.py", "--config", "mnist_mlp", "--device", "tpu"],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
        env={**os.environ, "TPU_HEALTH_CMD": FAKE_HANG, "TPU_HEALTH_TIMEOUT": "1"},
    )
    assert r.returncode == 2, (r.stdout, r.stderr)
    assert "probe failed" in r.stderr and "hanging" in r.stderr


def test_train_device_tpu_cpu_only_gives_clean_error():
    r = subprocess.run(
        [sys.executable, "train.py", "--config", "mnist_mlp", "--device", "tpu"],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
        env={**os.environ, "TPU_HEALTH_CMD": FAKE_CPU},
    )
    assert r.returncode == 2, (r.stdout, r.stderr)
    assert "no TPU reachable" in r.stderr


def test_bench_emits_headline_json_when_budget_exhausted():
    """bench.py's one driver-parsed JSON line must land even when the
    global budget leaves no room for any section (VERDICT r3 item 1):
    every section is skipped, value is 0, and the note says why."""
    r = subprocess.run(
        [sys.executable, "bench.py"],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=300,
        env={
            **os.environ,
            "BENCH_DEVICE": "cpu",  # skips the TPU preflight
            "BENCH_TOTAL_BUDGET": "10",  # below the per-section floor
        },
    )
    assert r.returncode == 0, r.stderr[-2000:]
    line = [l for l in r.stdout.splitlines() if l.startswith("{")][-1]
    out = json.loads(line)
    assert out["unit"] == "imgs/sec/chip" and out["value"] == 0.0
    assert out["vs_baseline"] == 0.0
    assert "budget exhausted" in json.dumps(out)
    assert out["preflight"]["skipped"].startswith("BENCH_DEVICE")


def test_bench_wedged_preflight_skips_tpu_sections():
    """With a wedged tunnel the preflight fails fast and bench.py still
    emits the headline line: TPU sections are skipped with an honest
    note, CPU sections are attempted (and here budget-skipped)."""
    r = subprocess.run(
        [sys.executable, "bench.py"],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=300,
        env={
            **os.environ,
            "TPU_HEALTH_CMD": FAKE_HANG,
            "BENCH_PREFLIGHT_TIMEOUT": "2",
            "BENCH_TOTAL_BUDGET": "40",  # preflight fits, sections don't
        },
    )
    assert r.returncode == 0, r.stderr[-2000:]
    line = [l for l in r.stdout.splitlines() if l.startswith("{")][-1]
    out = json.loads(line)
    assert out["value"] == 0.0
    assert "preflight" in out and out["preflight"]["alive"] is False
    assert "TPU sections skipped" in out["note"]
    assert "fed_input" not in out  # never scheduled without a tunnel


def test_bench_sigterm_lands_partial_json():
    """The driver's timeout delivers SIGTERM before SIGKILL; bench.py
    must use that window to print the partial headline line (round 3's
    rc=124/empty-tail failure mode)."""
    import signal
    import time

    proc = subprocess.Popen(
        [sys.executable, "bench.py"],
        cwd=REPO,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env={
            **os.environ,
            "BENCH_DEVICE": "cpu",
            "BENCH_TOTAL_BUDGET": "3000",  # roomy: sections would run
        },
    )
    time.sleep(5)  # inside the first (slow) section's child
    proc.send_signal(signal.SIGTERM)
    out, err = proc.communicate(timeout=60)
    assert proc.returncode == 0, err[-2000:]
    line = [l for l in out.splitlines() if l.startswith("{")][-1]
    parsed = json.loads(line)
    assert parsed["unit"] == "imgs/sec/chip"
    assert "signal 15" in parsed["note"]
