"""Full-scale configs trace + shard without execution (VERDICT weak #5).

The 1-chip box can never RUN `bert_mlm` full (world 32, BERT-base) or
`llama_lora` full (4x4 torus x tp=4 = 64 devices, Llama-2-7B), but
shape/sharding-rule bugs in them are catchable: build the real full-scale
bundle, `jax.eval_shape` the stacked state (no buffers materialize), bind
it to a 64-device virtual CPU mesh with the config's sharding rules, and
`.lower()` the actual collective train step — tracing + SPMD partitioning
with zero FLOPs. Runs in a subprocess because the suite conftest pins the
8-device mesh.
"""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = r"""
import json
import os

flags = os.environ.get("XLA_FLAGS", "")
flags = " ".join(f for f in flags.split() if "device_count" not in f)
os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=64").strip()

import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp

from consensusml_tpu import configs
from consensusml_tpu.comm import WorkerMesh
from consensusml_tpu.train import init_stacked_state, make_collective_train_step

out = {}


def lower_one(name, model_axes, rules, batch_maker):
    bundle = configs.build(name, "full")
    world = bundle.world_size
    per = 1
    for _, s in model_axes:
        per *= s
    wmesh = WorkerMesh.create(
        bundle.cfg.gossip.topology,
        devices=jax.devices()[: world * per],
        model_axes=model_axes,
    )
    state_sds = jax.eval_shape(
        lambda k: init_stacked_state(
            bundle.cfg, bundle.init_params, k, world
        ),
        jax.random.key(0),
    )
    shardings = wmesh.stacked_shardings(state_sds, rules=rules)
    state_in = jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        state_sds,
        shardings,
        is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct),
    )
    batch_sds = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(
            s.shape, s.dtype, sharding=wmesh.stacked_sharding()
        ),
        batch_maker(bundle),
    )
    step = make_collective_train_step(bundle.cfg, bundle.loss_fn, wmesh)
    jitted = getattr(step, "_jitted", step)
    with jax.sharding.set_mesh(wmesh.mesh):
        lowered = jitted.lower(state_in, batch_sds)
    text = lowered.as_text()
    return state_in, {"hlo_len": len(text), "world": world, "per_worker": per}


# ---- bert_mlm full: 32-worker ring, BERT-base, no model axes ----
def bert_batch(bundle):
    b = bundle  # (W, H, B, S) int32 MLM triple — shapes only, no sampling
    return {
        "input_ids": jax.ShapeDtypeStruct((32, 8, 32, 128), jnp.int32),
        "labels": jax.ShapeDtypeStruct((32, 8, 32, 128), jnp.int32),
        "mlm_mask": jax.ShapeDtypeStruct((32, 8, 32, 128), jnp.float32),
    }


state_in, info = lower_one("bert_mlm", (), None, bert_batch)
# every leaf shards its leading worker axis 32-way
leaf = jax.tree.leaves(state_in.params)[0]
info["param0_global"] = list(leaf.shape)
info["param0_shard"] = list(leaf.sharding.shard_shape(leaf.shape))
assert info["param0_shard"][0] == 1 and info["param0_global"][0] == 32
out["bert_mlm"] = info

# ---- llama_lora full: 4x4 torus x tp=4 (64 devices), 7B weights ----
from consensusml_tpu.parallel import llama_tp_rules


def llama_batch(bundle):
    return {"input_ids": jax.ShapeDtypeStruct((16, 1, 8, 2048), jnp.int32)}


state_in, info = lower_one(
    "llama_lora", (("tp", 4),), llama_tp_rules("tp"), llama_batch
)
flat = jax.tree_util.tree_flatten_with_path(state_in.params)[0]
def find(frag):
    for p, leaf in flat:
        if frag in jax.tree_util.keystr(p, simple=True, separator="/"):
            return leaf
    raise KeyError(frag)

emb = find("tok_emb/embedding")
info["emb_global"] = list(emb.shape)
info["emb_shard"] = list(emb.sharding.shard_shape(emb.shape))
# (16, 32000, 4096) -> one worker, hidden split 4-way
assert info["emb_shard"] == [1, emb.shape[1], emb.shape[2] // 4], info
q = find("q_proj/base/kernel")
info["q_shard"] = list(q.sharding.shard_shape(q.shape))
assert info["q_shard"] == [1, q.shape[1], q.shape[2] // 4], info
down = find("down_proj/kernel")
info["down_shard"] = list(down.sharding.shard_shape(down.shape))
assert info["down_shard"] == [1, down.shape[1] // 4, down.shape[2]], info
out["llama_lora"] = info

# ---- gpt2_topk full: 8-worker ring, GPT-2-medium, CHOCO compressed
# gossip (uint16 local-index payloads ride the ppermutes at full scale)
def gpt2_batch(bundle):
    return {"input_ids": jax.ShapeDtypeStruct((8, 2, 8, 1024), jnp.int32)}


state_in, info = lower_one("gpt2_topk", (), None, gpt2_batch)
gossip_leaves = jax.tree.leaves(state_in.gossip)
info["choco_state_leaves"] = len(gossip_leaves)
assert info["choco_state_leaves"] > 0  # xhat/s tracked per gossiped leaf
out["gpt2_topk"] = info

print("RESULT " + json.dumps(out))
"""


def test_fullscale_bert_and_llama_tp_lower():
    proc = subprocess.run(
        [sys.executable, "-c", _CHILD],
        capture_output=True,
        text=True,
        timeout=1800,
        cwd=REPO,
    )
    assert proc.returncode == 0, (proc.stderr[-2500:], proc.stdout[-500:])
    line = [l for l in proc.stdout.splitlines() if l.startswith("RESULT ")][-1]
    out = json.loads(line[len("RESULT "):])
    assert out["bert_mlm"]["hlo_len"] > 1000
    assert out["llama_lora"]["hlo_len"] > 1000
    assert out["llama_lora"]["per_worker"] == 4
    assert out["gpt2_topk"]["hlo_len"] > 1000
    assert out["gpt2_topk"]["choco_state_leaves"] > 0
