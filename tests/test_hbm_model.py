"""HBM accounting model (tools/hbm_model.py).

The state components are EXACT claims (eval_shape bytes), so they are
pinned against actually-initialized state. The activation term is a
model; its on-chip validation against measured device peak lives in the
slow TPU tier (runs only where a real accelerator is attached).
"""

import math
import os
import sys

import jax
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

import hbm_model  # noqa: E402

from consensusml_tpu.configs import build  # noqa: E402
from consensusml_tpu.train import init_stacked_state  # noqa: E402


def _leaf_bytes(tree) -> int:
    return sum(
        math.prod(x.shape) * x.dtype.itemsize for x in jax.tree.leaves(tree)
    )


@pytest.mark.parametrize(
    "name",
    [
        "mnist_mlp",
        # the larger smoke states take ~20 s each to initialize: slow tier
        pytest.param("gpt2_topk", marks=pytest.mark.slow),
        pytest.param("cifar_resnet50", marks=pytest.mark.slow),
    ],
)
def test_state_components_match_real_state(name):
    """predict()'s params/opt/gossip bytes equal the bytes of the state a
    run actually allocates (per worker)."""
    pred = hbm_model.predict(name, "smoke")["per_device"]
    bundle = build(name, "smoke")
    state = init_stacked_state(
        bundle.cfg, bundle.init_params, jax.random.key(0), 1
    )
    assert pred["params"] == _leaf_bytes(state.params)
    assert pred["model_state"] == _leaf_bytes(state.model_state)
    assert pred["opt"] == _leaf_bytes(state.opt_state)
    assert pred["gossip"] == _leaf_bytes(state.gossip)


def test_tp_division_shards_matched_leaves_only():
    """With model axes, leaves a sharding rule matches shrink by the axis
    product; unmatched (replicated) leaves do not."""
    base = hbm_model.predict("llama_lora", "smoke", model_axes=())
    tp4 = hbm_model.predict("llama_lora", "smoke", model_axes=(("tp", 4),))
    p0, p4 = base["per_device"]["params"], tp4["per_device"]["params"]
    # matmul weights dominate llama params: tp=4 must cut params to
    # between 1/4 (everything sharded) and 1/2 (half the bytes sharded)
    assert p0 / 4 <= p4 < p0 / 2, (p0, p4)
    # norms/biases are replicated, so it cannot be a clean /4
    assert p4 > p0 / 4, (p0, p4)


def test_codec_terms_present_only_for_compressed_configs():
    gpt2 = hbm_model.predict("gpt2_topk", "smoke")["per_device"]
    mlp = hbm_model.predict("mnist_mlp", "smoke")["per_device"]
    assert gpt2["codec_temp"] > 0 and gpt2["payloads"] > 0
    assert mlp["codec_temp"] == 0 and mlp["payloads"] == 0
    # CHOCO keeps xhat+s per wire bucket: exactly 2x the f32 compress
    # domain with leaf sizes rounded up to the codec chunk (the bucketed
    # state layout — docs/gossip_bucketing.md)
    bundle = build("gpt2_topk", "smoke")
    probe = jax.eval_shape(bundle.init_params, jax.random.key(0))
    plan = bundle.cfg.engine().bucket_plan({"params": probe, "model_state": {}})
    n_params = gpt2["params"]  # f32 leaves
    assert gpt2["gossip"] == 2 * 4 * plan.total_elems >= 2 * n_params


@pytest.mark.slow  # builds all five FULL bundles (llama-7B eval_shape)
def test_full_scale_predictions_fit_claimed_hardware():
    """The doc's pod-fit claims, as assertions: every full-scale config's
    per-device prediction fits a v4 chip's 32 GiB HBM; the single-chip
    workloads fit a v5e's 16 GiB."""
    v4, v5e = 32 * hbm_model.GIB, 16 * hbm_model.GIB
    for name in ("mnist_mlp", "cifar_resnet50", "bert_mlm", "gpt2_topk",
                 "llama_lora"):
        peak = hbm_model.predict(name, "full")["predicted_peak_bytes"]
        assert peak < v4, f"{name}: {peak / hbm_model.GIB:.1f} GiB > v4 HBM"
    for name in ("mnist_mlp", "cifar_resnet50", "bert_mlm"):
        peak = hbm_model.predict(name, "full")["predicted_peak_bytes"]
        assert peak < v5e, f"{name}: {peak / hbm_model.GIB:.1f} GiB > v5e HBM"


@pytest.mark.slow
def test_predicted_vs_measured_on_accelerator():
    """On a real chip: predicted peak within tolerance of the device
    truth (XLA's compiled buffer assignment; runtime memory_stats where
    available) for a runnable full-scale workload, world=1 — exactly the
    per-device layout predict() models."""
    if jax.default_backend() not in ("tpu", "axon"):
        pytest.skip("needs a real accelerator backend")
    pred = hbm_model.predict("cifar_resnet50", "full", world=1)
    got = hbm_model.measure("cifar_resnet50", "full")
    peak = got.get("measured_peak_bytes") or got["compiled_peak_bytes"]
    ratio = pred["predicted_peak_bytes"] / peak
    # measured on this chip: 1.05 (cifar_resnet50) and 1.03 (gpt2_topk)
    assert 0.85 <= ratio <= 1.15, (pred, got)
