"""Tests for the compression codecs (jnp reference implementations).

Covers the reference's CUDA kernel layer semantics (SURVEY.md L0):
round-trip correctness, static payload shapes, wire-size accounting, and
jit/vmap compatibility (payloads must ride ppermute, so they must be
well-formed pytrees under transformation).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from consensusml_tpu.compress import (
    Int8Compressor,
    TopKCompressor,
    topk_int8_compressor,
)


def test_topk_selects_largest_magnitude():
    x = jnp.asarray([0.1, -5.0, 0.2, 3.0, -0.05, 0.0])
    p = TopKCompressor(k=2).compress(x)
    assert sorted(np.asarray(p.indices).tolist()) == [1, 3]
    out = TopKCompressor(k=2).decompress(p)
    np.testing.assert_allclose(out, [0.0, -5.0, 0.0, 3.0, 0.0, 0.0])


def test_topk_static_shapes_and_ratio():
    x = jnp.zeros((64, 32))
    comp = TopKCompressor(ratio=0.01)
    p = jax.eval_shape(comp.compress, x)
    assert p.values.shape == (int(round(64 * 32 * 0.01)),)
    assert p.indices.dtype == jnp.int32
    # k never collapses to zero
    p1 = jax.eval_shape(TopKCompressor(ratio=1e-9).compress, jnp.zeros(10))
    assert p1.values.shape == (1,)


def test_topk_preserves_dtype_and_shape():
    x = jnp.asarray(np.random.default_rng(0).normal(size=(7, 9)), jnp.bfloat16)
    comp = TopKCompressor(ratio=0.25)
    out = comp.decompress(comp.compress(x))
    assert out.shape == x.shape and out.dtype == x.dtype


def test_topk_under_jit_and_vmap():
    comp = TopKCompressor(ratio=0.5)
    x = jnp.asarray(np.random.default_rng(1).normal(size=(4, 20)), jnp.float32)
    roundtrip = lambda v: comp.decompress(comp.compress(v))
    got = jax.jit(jax.vmap(roundtrip))(x)
    want = np.stack([np.asarray(roundtrip(row)) for row in x])
    np.testing.assert_allclose(got, want)


def test_int8_roundtrip_error_bound():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(1000,)), jnp.float32)
    comp = Int8Compressor(chunk=128)
    p = comp.compress(x)
    assert p.data.dtype == jnp.int8
    out = comp.decompress(p)
    # max error per element <= scale/2 = absmax/254 per chunk
    err = np.abs(np.asarray(out) - np.asarray(x))
    scales = np.asarray(p.scales)
    bound = np.repeat(scales, 128)[: x.size] / 2 + 1e-7
    assert (err <= bound).all()


def test_int8_zero_chunks_and_padding():
    x = jnp.concatenate([jnp.zeros(300), jnp.ones(50)])  # pads to 512 w/ chunk 256
    comp = Int8Compressor(chunk=256)
    out = comp.decompress(comp.compress(x))
    np.testing.assert_allclose(out, x, atol=1e-6)
    assert out.shape == (350,)


def test_int8_exact_at_extremes():
    """absmax elements quantize exactly (q = +/-127)."""
    x = jnp.asarray([-4.0, 2.0, 4.0, 1.0])
    comp = Int8Compressor(chunk=4)
    p = comp.compress(x)
    out = comp.decompress(p)
    assert float(out[0]) == pytest.approx(-4.0)
    assert float(out[2]) == pytest.approx(4.0)


def test_composed_topk_int8():
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(512,)) * 10, jnp.float32)
    comp = topk_int8_compressor(ratio=0.125, chunk=64)
    p = comp.compress(x)
    assert p.values.data.dtype == jnp.int8  # nested payload: int8 of topk values
    out = comp.decompress(p)
    # support = top-64 magnitudes, values within int8 error of originals
    idx = np.asarray(p.indices)
    dense = np.asarray(x)
    np.testing.assert_allclose(
        np.asarray(out)[idx], dense[idx], atol=np.abs(dense).max() / 100
    )
    mask = np.ones(512, bool)
    mask[idx] = False
    assert np.all(np.asarray(out)[mask] == 0)


def test_wire_bytes_accounting():
    comp = TopKCompressor(ratio=0.01)
    dense_bytes = 10000 * 4
    wire = comp.wire_bytes((100, 100), jnp.float32)
    assert wire == 100 * 4 + 100 * 4  # 100 f32 values + 100 i32 indices
    assert wire < dense_bytes / 10
    q = Int8Compressor(chunk=256).wire_bytes((100, 100), jnp.float32)
    assert q == 10240 * 1 + 40 * 4  # padded int8 data + 40 f32 scales


def test_decompress_accumulate_matches_dense_axpy():
    """Fused receive == decompress + weighted add, for every codec family
    (SURVEY.md §2 native component 3)."""
    from consensusml_tpu.compress import (
        ChunkedTopKCompressor,
        IdentityCompressor,
        PallasInt8Compressor,
    )

    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.normal(size=(37, 19)), jnp.float32)
    acc = jnp.asarray(rng.normal(size=(37, 19)), jnp.float32)
    codecs = [
        TopKCompressor(ratio=0.1),
        Int8Compressor(chunk=128),
        topk_int8_compressor(ratio=0.2, chunk=128),
        ChunkedTopKCompressor(chunk=128, k_per_chunk=4, impl="jnp"),
        PallasInt8Compressor(chunk=128, impl="jnp"),
        IdentityCompressor(),
    ]
    for comp in codecs:
        p = comp.compress(x)
        want = acc + 0.3 * jnp.asarray(comp.decompress(p), jnp.float32)
        got = comp.decompress_accumulate(p, acc, 0.3)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-6,
            err_msg=type(comp).__name__,
        )


def test_decompress_accumulate_tree():
    comp = TopKCompressor(ratio=0.5)
    rng = np.random.default_rng(8)
    tree = {
        "a": jnp.asarray(rng.normal(size=(8,)), jnp.float32),
        "b": jnp.asarray(rng.normal(size=(3, 5)), jnp.float32),
    }
    acc = jax.tree.map(lambda v: jnp.asarray(rng.normal(size=v.shape), v.dtype), tree)
    q = comp.compress_tree(tree)
    want = jax.tree.map(
        lambda a, d: a + 2.0 * d, acc, comp.decompress_tree(q, like=tree)
    )
    got = comp.decompress_accumulate_tree(q, acc, 2.0)
    for ka in tree:
        np.testing.assert_allclose(np.asarray(got[ka]), np.asarray(want[ka]), rtol=1e-6)


def test_fused_receive_memory_beats_dense_decode():
    """SURVEY §2 component 3, the measured claim: the fused scatter-add
    receive must compile to materially less temp memory than dense decode
    + axpy for a sparse payload on a large tensor."""
    comp = TopKCompressor(ratio=0.001)
    x = jnp.zeros((2048, 2048), jnp.float32)
    p = comp.compress(x)
    acc = jnp.ones_like(x)

    fused = jax.jit(lambda p, a: comp.decompress_accumulate(p, a, 0.5))
    dense = jax.jit(lambda p, a: a + 0.5 * comp.decompress(p))
    try:
        f_tmp = fused.lower(p, acc).compile().memory_analysis().temp_size_in_bytes
        d_tmp = dense.lower(p, acc).compile().memory_analysis().temp_size_in_bytes
    except (AttributeError, NotImplementedError):
        import pytest

        pytest.skip("memory_analysis unsupported on this backend")
    dense_tensor = 2048 * 2048 * 4
    assert d_tmp >= dense_tensor  # dense decode really materializes it
    assert f_tmp < dense_tensor // 2, (d_tmp, f_tmp)
