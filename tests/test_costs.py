"""Cost-attribution plane (ISSUE 11): compiled cost ledger, three-way
HBM reconciliation, on-demand /profile capture, xprof --json.

CPU tier-1 coverage for obs/costs.py + obs/memviz.py + the /profile
endpoint: every registered executable has a cost row, compile counters
are monotonic, the zero-recompile contract survives ledger wiring
(compile_counts unchanged through a serving e2e), the analytic vs
compiled vs live reconciliation lands within a loose CPU band, /profile
is single-flight with dir-quota rotation, and xprof_summary's family
grouping no longer merges distinct dotted kernel names.
"""

import gzip
import importlib.util
import json
import os
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from consensusml_tpu.obs.costs import CostLedger
from consensusml_tpu.obs.memviz import (
    HbmAccountant,
    compiled_footprint,
    live_array_bytes,
    reconcile_config,
)
from consensusml_tpu.obs.metrics import MetricsRegistry, parse_metric_key

pytestmark = pytest.mark.profiling

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _xprof_tool():
    spec = importlib.util.spec_from_file_location(
        "xprof_summary", os.path.join(REPO, "tools", "xprof_summary.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _tiny_engine(reg=None, **cfg):
    from consensusml_tpu.models.gpt2 import GPT2Config, GPT2LM
    from consensusml_tpu.serve import Engine, ServeConfig

    model = GPT2LM(
        config=GPT2Config(
            vocab_size=64, hidden=32, layers=2, heads=2, max_len=64,
            dropout=0.0,
        )
    )
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return Engine(
        model, params,
        ServeConfig(num_slots=4, max_len=64, max_new_tokens=8, **cfg),
    )


# ---------------------------------------------------------------------------
# the ledger
# ---------------------------------------------------------------------------


def test_ledger_row_carries_cost_memory_and_compile_time():
    reg = MetricsRegistry()
    led = CostLedger(registry=reg)
    f = jax.jit(lambda x: (x @ x).sum())
    row = led.register(
        "toy.matmul", f, jax.ShapeDtypeStruct((64, 64), jnp.float32)
    )
    assert row.flops > 0 and row.bytes_accessed > 0
    assert row.compile_s > 0
    assert row.peak_bytes == (
        row.argument_bytes + row.temp_bytes + row.output_bytes
        - row.alias_bytes
    )
    # the row landed on the labeled gauge families
    keys = {m.key for m in reg.metrics()}
    assert 'consensusml_cost_flops{executable="toy.matmul"}' in keys
    assert 'consensusml_compile_seconds{executable="toy.matmul"}' in keys


def test_compile_counters_are_monotonic():
    reg = MetricsRegistry()
    led = CostLedger(registry=reg)
    f = jax.jit(lambda x: x * 2)
    x = jax.ShapeDtypeStruct((8,), jnp.float32)
    led.register("a", f, x)
    n1 = reg.counter("consensusml_compile_total").value
    s1 = reg.counter("consensusml_compile_seconds_total").value
    led.register("b", f, x)
    led.register("a", f, x)  # re-register still counts a compile
    n2 = reg.counter("consensusml_compile_total").value
    s2 = reg.counter("consensusml_compile_seconds_total").value
    assert n2 == n1 + 2
    assert s2 > s1
    # transfers are not compiles
    led.register_transfer("stage", jnp.ones((16,)))
    assert reg.counter("consensusml_compile_total").value == n2


def test_attribution_pairs_expected_and_measured():
    led = CostLedger(
        registry=MetricsRegistry(),
        peak_flops_per_s=1e9,
        peak_bytes_per_s=1e9,
    )
    f = jax.jit(lambda x: (x @ x).sum())
    row = led.register(
        "toy", f, jax.ShapeDtypeStruct((64, 64), jnp.float32)
    )
    attr = led.observe_measured("toy", 0.01)
    assert attr["bound"] in ("compute", "memory")
    assert attr["expected_s"] == pytest.approx(
        max(row.flops, row.bytes_accessed) / 1e9
    )
    assert attr["ratio_to_floor"] == pytest.approx(
        0.01 / attr["expected_s"]
    )
    assert attr["unattributed_s"] == pytest.approx(
        0.01 - attr["expected_s"]
    )
    with pytest.raises(KeyError):
        led.observe_measured("nope", 1.0)


def test_transfer_rows_floor_on_staging_bandwidth():
    """Transfer rows floor against the host<->device staging bandwidth,
    NOT the HBM-bus anchor compiled rows use — the hot-swap stage at
    line rate must read ~1x its floor, not 30x over."""
    led = CostLedger(
        registry=MetricsRegistry(),
        peak_bytes_per_s=1e12,  # deliberately absurd HBM anchor
        peak_transfer_bytes_per_s=1e9,
    )
    led.register_transfer("stage", {"w": jnp.ones((1000,), jnp.float32)})
    attr = led.attribution("stage")
    assert attr["bound"] == "transfer"
    assert attr["expected_s"] == pytest.approx(4000 / 1e9)


def test_every_serving_executable_has_a_cost_row():
    reg = MetricsRegistry()
    led = CostLedger(registry=reg)
    with _tiny_engine() as eng:
        rows = eng.register_costs(led)
        expected = {f"serve.prefill.b{b}" for b in eng.buckets}
        expected |= {
            "serve.decode", "serve.decode.fused", "serve.hotswap.stage"
        }
        assert set(rows) == expected
        assert set(led.names()) == expected
        for name in expected:
            r = led.row(name)
            assert r is not None
            if r.kind == "compiled":
                assert r.flops > 0 and r.compile_s > 0
            else:
                assert r.argument_bytes > 0  # the staged params bytes
        # decode's meta names the pool geometry the row was lowered at
        assert rows["serve.decode"].meta["num_slots"] == 4


def test_zero_recompile_contract_survives_ledger_wiring():
    """compile_counts() byte-identical across register_costs AND a
    served request mix afterwards — the ledger's AOT path must never
    touch the jit dispatch caches."""
    led = CostLedger(registry=MetricsRegistry())
    with _tiny_engine() as eng:
        before = eng.warmup()
        eng.register_costs(led)
        assert eng.compile_counts() == before
        handles = [
            eng.submit([1 + i % 30] * (3 + i % 7)) for i in range(8)
        ]
        for h in handles:
            assert h.result(timeout=300).finish_reason in (
                "max_tokens", "eos"
            )
        assert eng.compile_counts() == before


def test_pool_hbm_gauges_track_free_blocks():
    from consensusml_tpu.obs import get_registry

    reg = get_registry()
    with _tiny_engine() as eng:
        total = reg.gauge("consensusml_pool_hbm_bytes").value
        free0 = reg.gauge("consensusml_pool_hbm_free_bytes").value
        # full headroom at init (trash block excluded from free)
        assert total > 0 and 0 < free0 < total
        assert free0 == eng._pool.free_blocks * eng._block_nbytes
        assert reg.gauge("consensusml_serve_params_bytes").value > 0
        h = eng.submit([1, 2, 3, 4], max_new_tokens=8)
        h.result(timeout=300)
        # the decode path refreshed the headroom gauge mid-request: it
        # is sampled per decode step (while the stream's blocks are
        # held), so it reads BELOW the idle headroom — the pressure
        # signal a router sees during traffic
        free1 = reg.gauge("consensusml_pool_hbm_free_bytes").value
        assert 0 < free1 < free0


# ---------------------------------------------------------------------------
# HBM accounting + three-way reconciliation
# ---------------------------------------------------------------------------


def test_live_array_bytes_sees_new_arrays():
    before = live_array_bytes()["bytes"]
    keep = jnp.ones((1024, 256), jnp.float32)  # 1 MiB
    after = live_array_bytes()["bytes"]
    assert after - before >= keep.nbytes


def test_reconcile_sets_drift_gauges():
    reg = MetricsRegistry()
    acct = HbmAccountant(registry=reg)
    acct.tick()
    doc = acct.reconcile(analytic_bytes=120.0, compiled_bytes=100.0)
    assert doc["drift_pct"]["analytic_vs_compiled"] == pytest.approx(20.0)
    keys = {m.key for m in reg.metrics()}
    assert 'consensusml_hbm_drift_pct{pair="analytic_vs_compiled"}' in keys
    assert "consensusml_hbm_live_bytes" in keys


def test_three_way_reconciliation_on_tiny_config():
    """Analytic vs compiled vs live for mnist_mlp smoke at world=1.

    CPU band is deliberately loose: the activation coefficients model
    TPU scheduling and the live side is a floor without memory_stats —
    but all three must land within the SAME order of magnitude, and the
    state-dominated analytic-vs-compiled pair much closer than that.
    """
    reg = MetricsRegistry()
    led = CostLedger(registry=reg)
    doc = reconcile_config("mnist_mlp", "smoke", registry=reg, ledger=led)
    a, c, l = (
        doc["analytic_bytes"], doc["compiled_bytes"], doc["live_peak_bytes"]
    )
    assert a > 0 and c > 0 and l > 0
    assert 0.25 < a / c < 4.0, (a, c)
    assert 0.25 < c / max(l, 1) < 4.0, (c, l)
    for pair in ("analytic_vs_compiled", "compiled_vs_live",
                 "analytic_vs_live"):
        assert pair in doc["drift_pct"]
    # the compiled side came through the ledger: the row exists
    assert led.row("train.step.mnist_mlp") is not None


def test_compiled_footprint_matches_hbm_model_measure_definition():
    f = jax.jit(lambda x: (x @ x).sum())
    ma = (
        f.lower(jax.ShapeDtypeStruct((32, 32), jnp.float32))
        .compile()
        .memory_analysis()
    )
    assert compiled_footprint(ma) == (
        ma.argument_size_in_bytes + ma.temp_size_in_bytes
        + ma.output_size_in_bytes - ma.alias_size_in_bytes
    )


# ---------------------------------------------------------------------------
# /profile endpoint
# ---------------------------------------------------------------------------


def _get(url, timeout=60):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_profile_endpoint_single_flight_and_rotation(tmp_path):
    from consensusml_tpu.obs import MetricsServer

    reg = MetricsRegistry()
    srv = MetricsServer(
        registry=reg, profile_dir=str(tmp_path), profile_quota=2
    )
    try:
        results = {}

        def first():
            results["a"] = _get(srv.url("/profile?ms=700"))

        t = threading.Thread(target=first)
        t.start()
        time.sleep(0.25)  # the first capture is mid-window now
        code_b, doc_b = _get(srv.url("/profile?ms=50"))
        t.join()
        code_a, doc_a = results["a"]
        # the concurrent double-request contract: second gets 409 + the
        # in-flight capture id, never two overlapping profiler sessions
        assert code_a == 200 and code_b == 409
        assert doc_b["capture_id"] == doc_a["capture_id"]
        assert doc_a["trace_json"] and os.path.exists(doc_a["trace_json"])
        assert reg.counter("consensusml_profile_rejected_total").value == 1

        # two more captures -> quota 2 leaves exactly 2 dirs, newest kept
        code_c, doc_c = _get(srv.url("/profile?ms=50"))
        code_d, doc_d = _get(srv.url("/profile?ms=50"))
        assert code_c == code_d == 200
        caps = sorted(
            d for d in os.listdir(str(tmp_path)) if d.startswith("cap-")
        )
        assert len(caps) == 2
        assert os.path.basename(doc_d["dir"]) in caps
        assert not os.path.exists(doc_a["dir"])  # oldest rotated out
        assert reg.counter("consensusml_profile_captures_total").value == 3
    finally:
        srv.close()


def test_profile_capture_parses_via_xprof_summary_json(tmp_path):
    """Acceptance: /profile on a LIVE ServeServer yields a capture that
    xprof_summary --json parses (machine-readable op/host tables)."""
    import socket

    from consensusml_tpu.serve.server import ServeServer

    with _tiny_engine() as eng:
        eng.warmup()
        srv = ServeServer(eng, port=0, metrics_port=0)
        srv.metrics.profile_dir = str(tmp_path)
        try:
            results: dict = {}

            def cap():
                results["r"] = _get(srv.metrics.url("/profile?ms=600"))

            t = threading.Thread(target=cap)
            t.start()
            # real traffic through the live socket while the capture runs
            with socket.create_connection(srv.address, timeout=30) as s:
                s.sendall(
                    (json.dumps({"ids": [1, 2, 3], "max_new_tokens": 4})
                     + "\n").encode()
                )
                f = s.makefile()
                while True:
                    line = json.loads(f.readline())
                    if "tokens" in line or "error" in line:
                        break
                assert "tokens" in line
            t.join()
            code, doc = results["r"]
            assert code == 200 and doc["trace_json"]
            # the endpoint already linked the machine-readable summary
            assert doc["summary"] is not None
            assert "device_total_ms" in doc["summary"]
            # ... and the CLI parses the same capture standalone
            mod = _xprof_tool()
            out = mod.summarize(doc["trace_json"])
            assert out["event_count"] > 0
            assert isinstance(out["ops"], list)
        finally:
            srv.shutdown(drain=False)


# ---------------------------------------------------------------------------
# xprof_summary: --json + the .N family fix
# ---------------------------------------------------------------------------


def _write_trace(path, names_durs):
    ev = [
        {
            "ph": "M", "name": "process_name", "pid": 1,
            "args": {"name": "/device:TPU:0"},
        }
    ]
    for name, dur in names_durs:
        ev.append({"ph": "X", "pid": 1, "name": name, "dur": dur, "ts": 0})
    with gzip.open(path, "wt") as f:
        json.dump({"traceEvents": ev}, f)


def test_op_family_grouping_keeps_distinct_dotted_kernels(tmp_path):
    """XLA duplicates (`fusion`, `fusion.1`) merge; two pallas kernels
    whose FAMILY names differ only by a numeric dotted suffix
    (`fused_pack.4` vs `fused_pack.8`, no bare sibling) stay distinct —
    the old unconditional `.N` strip merged them into one bogus row."""
    p = str(tmp_path / "t.trace.json.gz")
    _write_trace(
        p,
        [
            ("fusion", 100), ("fusion.1", 50), ("fusion.2", 25),
            ("fused_pack.4", 10), ("fused_pack.8", 20),
        ],
    )
    mod = _xprof_tool()
    out = mod.summarize(p)
    ops = {o["op"]: o["ms"] for o in out["ops"]}
    assert ops["fusion"] == pytest.approx(0.175, abs=0.01)  # 175 us merged
    assert "fusion.1" not in ops and "fusion.2" not in ops
    assert "fused_pack.4" in ops and "fused_pack.8" in ops
    assert "fused_pack" not in ops


def test_xprof_summary_json_cli(tmp_path, capsys):
    p = str(tmp_path / "t.trace.json.gz")
    _write_trace(p, [("fusion", 1000), ("copy.1", 500)])
    host = tmp_path / "host.json"
    host.write_text(json.dumps({
        "traceEvents": [
            {"ph": "X", "name": "train.round", "dur": 1500.0},
            {"ph": "X", "name": "train.round", "dur": 500.0},
        ]
    }))
    mod = _xprof_tool()
    import sys
    old = sys.argv
    try:
        sys.argv = ["xprof_summary", p, "--json", "--host-trace", str(host)]
        rc = mod.main()
    finally:
        sys.argv = old
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["device_total_ms"] == pytest.approx(1.5)
    assert doc["event_count"] == 2
    assert {o["op"] for o in doc["ops"]} == {"fusion", "copy.1"}
    assert doc["host_spans"][0]["span"] == "train.round"
    assert doc["host_spans"][0]["count"] == 2


# ---------------------------------------------------------------------------
# cluster aggregation carries the attribution table
# ---------------------------------------------------------------------------


def test_cluster_aggregate_builds_attribution_section(tmp_path):
    from consensusml_tpu.obs import ClusterWriter
    from consensusml_tpu.obs.cluster import aggregate

    reg = MetricsRegistry()
    led = CostLedger(
        registry=reg, peak_flops_per_s=1e9, peak_bytes_per_s=1e9
    )
    f = jax.jit(lambda x: (x @ x).sum())
    led.register("toy.step", f, jax.ShapeDtypeStruct((32, 32), jnp.float32))
    led.observe_measured("toy.step", 0.005)
    acct = HbmAccountant(registry=reg)
    acct.tick()
    acct.reconcile(analytic_bytes=110.0, compiled_bytes=100.0)
    ClusterWriter(str(tmp_path), rank=0, registry=reg).write(round=3)
    doc = aggregate(str(tmp_path))
    attr = {r["executable"]: r for r in doc["attribution"]}
    assert "toy.step" in attr
    row = attr["toy.step"]
    assert row["flops"] > 0 and row["compile_s"] > 0
    assert row["measured_s"] == pytest.approx(0.005)
    assert row["floor_ratio"] > 0
    assert doc["hbm"]["analytic_bytes"] == pytest.approx(110.0)
    assert doc["hbm"]["drift_pct"]["analytic_vs_compiled"] == pytest.approx(
        10.0
    )
