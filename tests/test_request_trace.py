"""Request-scoped tracing plane (ISSUE 10): TraceContext propagation,
the bounded RequestTrace registry, exemplar-bearing SLO histograms, the
live /metrics endpoint, and the serving-crash flight-recorder dump.

Acceptance anchors: a loadgen → ServeServer → Engine round-trip where
every completed request's trace carries submit → admission → prefill →
decode → completion (plus preemption and hot-swap events when induced),
and a p99 exemplar request_id that resolves to a real recorded trace on
both the client and server snapshots. All tier-1 fast.
"""

import json
import os
import sys
import threading
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from consensusml_tpu.obs import (
    FlightRecorder,
    MetricsRegistry,
    MetricsServer,
    RequestTraceRegistry,
    SpanTracer,
    TraceContext,
    get_request_registry,
    merged_chrome_trace,
)
from consensusml_tpu.obs.metrics import DEFAULT_SLO_BUCKETS

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

pytestmark = [pytest.mark.telemetry, pytest.mark.serving]


def _tiny_gpt2(max_len=32):
    from consensusml_tpu.models.gpt2 import GPT2Config, GPT2LM

    return GPT2LM(
        config=GPT2Config(
            vocab_size=64, hidden=32, layers=2, heads=2, max_len=max_len,
            dropout=0.0,
        )
    )


def _init(model, seq=8, seed=0):
    return model.init(
        jax.random.key(seed), jnp.zeros((1, seq), jnp.int32)
    )["params"]


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------


def test_trace_context_mint_and_explicit():
    a, b = TraceContext.mint("x"), TraceContext.mint("x")
    assert a.trace_id != b.trace_id
    assert a.request_id == a.trace_id + "/0"
    c = TraceContext("tid-1", "tid-1/7")
    assert (c.trace_id, c.request_id) == ("tid-1", "tid-1/7")
    assert TraceContext("tid-2").request_id == "tid-2/0"


def test_registry_records_stage_events_and_tick_counts():
    reg = RequestTraceRegistry()
    ctx = TraceContext("t1")
    reg.start(ctx, prompt_len=5, max_new_tokens=4)
    reg.event(ctx.request_id, "admission.defer", reason="budget")
    reg.event(ctx.request_id, "admission", slot=2, bucket=8)
    reg.event(ctx.request_id, "prefill", bucket=8, seconds=0.01)
    for _ in range(3):
        reg.decode_tick(ctx.request_id)
    reg.event(ctx.request_id, "hotswap", generation=4)
    reg.finish(ctx.request_id, "max_tokens", tokens=4)
    tr = reg.get(ctx.request_id)
    assert tr.finish_reason == "max_tokens"
    assert tr.decode_ticks == 3 and tr.defer_ticks == 1
    assert tr.generation == 4
    d = tr.to_dict()
    assert [e["name"] for e in d["events"]] == [
        "submit", "admission.defer", "admission", "prefill", "decode",
        "hotswap", "complete",
    ]
    # timestamps are monotone within the trace
    ts = [e["ts_us"] for e in d["events"]]
    assert ts == sorted(ts)
    # unknown / finished ids are no-ops, never raises
    reg.event("nope", "admission")
    reg.decode_tick(ctx.request_id)
    assert reg.get(ctx.request_id).decode_ticks == 3


def test_registry_is_bounded_both_ways():
    reg = RequestTraceRegistry(capacity=4, max_active=3)
    for i in range(6):
        reg.start(TraceContext(f"t{i}"), 1)
    assert reg.active_count() == 3  # oldest force-completed
    snap = reg.snapshot()
    assert len(snap["completed"]) <= 4
    truncated = [t for t in snap["completed"] if t["finish_reason"] == "truncated"]
    assert truncated, "evicted in-flight traces must be marked truncated"
    for i in range(6):
        reg.finish(f"t{i}/0", "done")
    assert reg.active_count() == 0
    assert len(reg.snapshot()["completed"]) == 4  # ring bound


def test_snapshot_carries_in_flight_traces():
    reg = RequestTraceRegistry()
    reg.start(TraceContext("open"), 3)
    snap = reg.snapshot()
    (active,) = snap["active"]
    assert active["request_id"] == "open/0"
    assert active["finish_reason"] is None
    json.dumps(snap)  # JSON-able as-is


def test_merged_chrome_trace_has_span_and_request_lanes():
    tracer = SpanTracer()
    reg = RequestTraceRegistry()
    with tracer.span("serve.decode_step", active=1):
        pass
    ctx = TraceContext("tr")
    reg.start(ctx, 2)
    reg.finish(ctx.request_id, "max_tokens")
    doc = merged_chrome_trace(tracer, reg)
    names = [e.get("name") for e in doc["traceEvents"]]
    assert "serve.decode_step" in names
    assert "request" in names and "req.submit" in names
    req = next(e for e in doc["traceEvents"] if e.get("name") == "request")
    assert req["ph"] == "X" and req["args"]["trace_id"] == "tr"


# ---------------------------------------------------------------------------
# exemplar-bearing histograms
# ---------------------------------------------------------------------------


def test_histogram_retains_worst_exemplars():
    r = MetricsRegistry()
    h = r.histogram("t_slo_seconds", buckets=DEFAULT_SLO_BUCKETS)
    for i in range(50):
        h.observe(0.001 * (i + 1), exemplar=f"req-{i}")
    h.observe(0.9)  # un-exemplared observations never displace ids
    ex = h.exemplars()
    assert len(ex) == 8
    assert ex[0]["id"] == "req-49" and ex[0]["value"] == pytest.approx(0.050)
    assert [e["value"] for e in ex] == sorted(
        (e["value"] for e in ex), reverse=True
    )
    vd = h.value_dict()
    assert vd["exemplars"][0]["id"] == "req-49"
    # exposition stays plain prometheus text (no OpenMetrics extension)
    assert "req-49" not in r.to_prometheus()


def test_cluster_merge_keeps_worst_exemplars():
    from consensusml_tpu.obs.cluster import _merge_hist

    a = MetricsRegistry().histogram("m", buckets=(0.1, 1.0))
    b = MetricsRegistry().histogram("m", buckets=(0.1, 1.0))
    a.observe(0.5, exemplar="a-slow")
    b.observe(2.0, exemplar="b-slower")
    merged = _merge_hist(a.value_dict(), b.value_dict())
    assert merged["count"] == 2
    assert merged["exemplars"][0]["id"] == "b-slower"
    assert merged["exemplars"][1]["id"] == "a-slow"


# ---------------------------------------------------------------------------
# live /metrics endpoint
# ---------------------------------------------------------------------------


def test_metrics_server_serves_live_registry_traces_and_requests():
    reg = MetricsRegistry()
    tracer = SpanTracer()
    rt = RequestTraceRegistry()
    reg.counter("t_live_total").inc(3)
    ctx = TraceContext("live")
    rt.start(ctx, 2)
    with MetricsServer(registry=reg, tracer=tracer, requests=rt) as ms:
        text = urllib.request.urlopen(ms.url("/metrics")).read().decode()
        assert "t_live_total 3" in text
        reg.counter("t_live_total").inc()  # LIVE: next scrape sees it
        text = urllib.request.urlopen(ms.url("/metrics")).read().decode()
        assert "t_live_total 4" in text
        traces = json.load(urllib.request.urlopen(ms.url("/traces")))
        assert any(
            e.get("name") == "request" for e in traces["traceEvents"]
        )
        reqs = json.load(urllib.request.urlopen(ms.url("/requests")))
        assert reqs["active"][0]["trace_id"] == "live"
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(ms.url("/nope"))


# ---------------------------------------------------------------------------
# flight recorder: serving-crash dump carries the request registry
# ---------------------------------------------------------------------------


def test_flight_recorder_dump_includes_request_traces(tmp_path):
    rt = RequestTraceRegistry()
    ctx = TraceContext("crash")
    rt.start(ctx, 4)
    rt.event(ctx.request_id, "admission", slot=0, bucket=8)
    rec = FlightRecorder(
        str(tmp_path / "fr"), tracer=SpanTracer(),
        registry=MetricsRegistry(), requests=rt,
    )
    path = rec.dump("unit-test")
    doc = json.load(open(path))
    (active,) = doc["request_traces"]["active"]
    assert active["request_id"] == "crash/0"
    assert [e["name"] for e in active["events"]] == ["submit", "admission"]


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
def test_engine_thread_crash_dumps_flight_recorder(tmp_path):
    """A serving crash (engine thread re-raises) must leave a flight
    dump whose request_traces section parses and shows the in-flight
    request — the previously-lost post-mortem state."""
    rt = RequestTraceRegistry()
    rec = FlightRecorder(
        str(tmp_path / "fr"), tracer=SpanTracer(),
        registry=MetricsRegistry(), requests=rt,
    )
    prev_hook = threading.excepthook
    try:
        rec.install(sigterm=False)
        ctx = TraceContext("dying")
        rt.start(ctx, 3)

        def engine_loop():
            raise RuntimeError("simulated device OOM mid-serving")

        t = threading.Thread(target=engine_loop, name="serve-engine")
        t.start()
        t.join(timeout=10)
        deadline = time.monotonic() + 10
        while rec.last_dump_path is None and time.monotonic() < deadline:
            time.sleep(0.02)
    finally:
        threading.excepthook = prev_hook
    assert rec.last_dump_path and os.path.exists(rec.last_dump_path)
    doc = json.load(open(rec.last_dump_path))
    assert doc["reason"].startswith("thread-exception-serve-engine")
    assert "simulated device OOM" in doc["detail"]
    (active,) = doc["request_traces"]["active"]
    assert active["request_id"] == "dying/0"


# ---------------------------------------------------------------------------
# concurrency: engine threads + watcher + live scrape racing appends
# ---------------------------------------------------------------------------


def test_tracer_registry_and_scrape_race_cleanly():
    """Engine-style writer threads (span appends, exemplar observes,
    trace events), a watcher-style thread (snapshots + chrome export)
    and a live /metrics scraper all race for a while; everything stays
    consistent and parseable throughout."""
    tracer = SpanTracer(capacity=256)
    reg = MetricsRegistry()
    rt = RequestTraceRegistry(capacity=64, max_active=64)
    h = reg.histogram("t_race_seconds", buckets=DEFAULT_SLO_BUCKETS)
    stop = threading.Event()
    errors: list[str] = []

    def guard(fn):
        def run():
            try:
                i = 0
                while not stop.is_set():
                    fn(i)
                    i += 1
            except Exception as e:  # pragma: no cover - the assertion
                errors.append(f"{type(e).__name__}: {e}")

        return run

    def writer(i):
        ctx = TraceContext(f"w{threading.get_ident()}-{i}")
        rt.start(ctx, 4)
        with tracer.span("serve.decode_step", active=i % 8):
            h.observe(0.0001 * (i % 100), exemplar=ctx.request_id)
        rt.decode_ticks((ctx.request_id,) * 4)
        rt.finish(ctx.request_id, "max_tokens", tokens=4)

    def watcher(i):
        reg.snapshot({"i": i})
        tracer.trace_events()
        rt.snapshot()

    with MetricsServer(registry=reg, tracer=tracer, requests=rt) as ms:
        def scraper(i):
            body = urllib.request.urlopen(ms.url("/metrics")).read()
            assert b"t_race_seconds_count" in body
            json.load(urllib.request.urlopen(ms.url("/requests")))

        threads = [
            threading.Thread(target=guard(fn))
            for fn in (writer, writer, writer, watcher, scraper)
        ]
        for t in threads:
            t.start()
        time.sleep(1.5)
        stop.set()
        for t in threads:
            t.join(timeout=10)
    assert errors == []
    assert h.count > 0 and len(h.exemplars()) == 8
    # every retained trace is internally consistent
    for tr in rt.completed():
        assert tr.finish_reason in ("max_tokens", "truncated")
    json.dumps(rt.snapshot())


# ---------------------------------------------------------------------------
# e2e acceptance: loadgen -> ServeServer -> Engine round-trip
# ---------------------------------------------------------------------------


class _StubWatcher:
    """One staged swap, engine-thread protocol only (take/reject/stop)."""

    def __init__(self, staged):
        self._staged = [staged]

    def take(self):
        return self._staged.pop() if self._staged else None

    def reject(self, staged=None):  # pragma: no cover - mismatch path
        raise AssertionError("same-tree swap must not be rejected")

    def stop(self):
        pass


def test_e2e_loadgen_server_engine_traces_and_exemplars(tmp_path, monkeypatch):
    """The acceptance round-trip: socket loadgen drives a ServeServer
    over a tight paged pool with a mid-traffic hot swap. Every completed
    request's trace carries submit→admission→prefill→decode→completion
    (preempt/hotswap events present where induced), and the p99 TTFT
    exemplars on BOTH the client and server snapshots resolve to real
    recorded traces in the merged report, joined by trace_id."""
    from consensusml_tpu.obs import ClusterWriter, aggregate, get_registry
    from consensusml_tpu.obs import metrics as metrics_mod
    from consensusml_tpu.obs import requests as requests_mod
    from consensusml_tpu.serve import Engine, ServeConfig, ServeServer
    from consensusml_tpu.serve.pool.hotswap import StagedSwap
    from tools.loadgen import _socket_submit, run_loadgen

    # fresh process-wide registries: earlier in-process serving runs
    # must not leak exemplars/traces into the acceptance assertions
    monkeypatch.setattr(metrics_mod, "_GLOBAL", MetricsRegistry())
    monkeypatch.setattr(requests_mod, "_GLOBAL", RequestTraceRegistry())
    rt = get_request_registry()
    model = _tiny_gpt2()
    params = _init(model)
    # 10 blocks cannot hold 4 full streams -> recompute-preemption fires
    engine = Engine(
        model, params,
        ServeConfig(
            num_slots=4, max_len=32, kv_impl="paged", block_size=8,
            num_blocks=10, max_new_tokens=8,
        ),
    )
    server = ServeServer(engine, metrics_port=0)
    try:
        engine.warmup()
        host, port = server.address
        report = run_loadgen(
            _socket_submit(host, port),
            n_requests=8, rate_rps=300.0, prompt_lens=(4, 16),
            vocab=64, max_new_tokens=8, seed=3,
        )
        assert report["errors"] == 0 and report["completed"] == 8

        # induce a drain-free hot swap under live streams: let the
        # streams become resident first, then stage the same tree as
        # generation 2 — the flip lands between two decode steps and
        # stamps every resident slot's trace
        long_handles = [
            engine.submit([7, 8, 9, 10], max_new_tokens=16,
                          trace=TraceContext(f"swp-{i}"))
            for i in range(3)
        ]
        deadline = time.monotonic() + 60
        while engine._table.num_active < 3 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert engine._table.num_active >= 3
        engine._watcher = _StubWatcher(
            StagedSwap(generation=2, params=engine._params, meta={})
        )
        results = [h.result(timeout=120) for h in long_handles]
        assert engine.generation == 2
        assert any(r.generation == 2 for r in results)

        # live /metrics on the serving side, fresh per scrape
        murl = (
            f"http://{server.metrics_address[0]}:"
            f"{server.metrics_address[1]}/metrics"
        )
        text = urllib.request.urlopen(murl).read().decode()
        assert "consensusml_serve_ttft_seconds_bucket" in text
    finally:
        server.shutdown(drain=True)

    # ---- every completed request: the full event chain ------------------
    done = {
        tr.request_id: tr
        for tr in rt.completed()
        if tr.finish_reason in ("eos", "max_tokens")
    }
    lg = [tr for rid, tr in done.items() if rid.startswith("lg3-")]
    assert len(lg) == 8  # client-minted ids reached the server verbatim
    for tr in done.values():
        names = [e["name"] for e in tr.to_dict()["events"]]
        for stage in ("submit", "admission", "prefill", "decode", "complete"):
            assert stage in names, (tr.request_id, names)
        assert names.index("submit") < names.index("admission")
        assert names.index("prefill") < names.index("decode")
        assert tr.decode_ticks > 0
    # induced events landed on the traces they belong to
    assert engine.stats()["evictions"] > 0
    preempted = [tr for tr in done.values() if tr.preemptions]
    assert preempted, "tight pool must have preempted at least one stream"
    for tr in preempted:  # re-admission after preemption is on the trace
        names = [e["name"] for e in tr.to_dict()["events"]]
        assert names.count("admission") >= 2
    swapped = [tr for rid, tr in done.items() if rid.startswith("swp-")]
    assert len(swapped) == 3
    assert any(
        "hotswap" in [e["name"] for e in tr.to_dict()["events"]]
        and tr.generation == 2
        for tr in swapped
    ), "the induced generation flip must land on a resident stream's trace"

    # ---- client + server snapshots: p99 exemplars resolve ---------------
    obs_dir = tmp_path / "obs"
    reg = get_registry()
    ClusterWriter(str(obs_dir), rank=0, role="serve", registry=reg).write(
        extra={"request_traces": rt.snapshot()}
    )
    ClusterWriter(str(obs_dir), rank=1, role="loadgen", registry=reg).write(
        extra={"report": report, "request_traces": rt.snapshot()}
    )
    doc = aggregate(str(obs_dir))
    req = doc["requests"]
    assert req["traces_indexed"] >= 11
    by_metric: dict = {}
    for row in req["slowest"]:
        by_metric.setdefault(row["metric"], []).append(row)
    for fam in ("consensusml_serve_ttft_seconds",
                "consensusml_loadgen_ttft_seconds"):
        rows = by_metric[fam]
        top = rows[0]  # worst-first == the p99-governing observation
        assert top["resolved"], (fam, top)
        assert top["request_id"] in done
        assert top["trace_id"] == done[top["request_id"]].trace_id
    # client and server rows of one request join on trace_id
    client_ids = {r["trace_id"] for r in by_metric["consensusml_loadgen_ttft_seconds"]}
    server_ids = {r["trace_id"] for r in by_metric["consensusml_serve_ttft_seconds"]}
    assert client_ids & server_ids, "no request seen from both sides"

    # the report renders the table + determinism of the merge
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "obs_report",
        os.path.join(os.path.dirname(__file__), "..", "tools", "obs_report.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main([str(obs_dir)]) == 0
