"""Serving subsystem: export, KV-cache decode, continuous batching.

The acceptance path (ISSUE 5): train a tiny config → ``--export-serving``
→ the engine serves ≥8 concurrent streams through the continuous batcher
with ZERO recompiles after warmup (compile-count AND jaxpr-asserted),
and export→serve prefill logits are BIT-EXACT against ``evaluate()``'s
consensus-mean eval path.
"""

import queue
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from consensusml_tpu import configs
from consensusml_tpu.serve import (
    Engine,
    ServeConfig,
    ServeServer,
    export_serving,
    load_engine,
    load_serving,
    serving_meta,
)
from consensusml_tpu.serve.decode import prefill_buckets
from consensusml_tpu.train import init_stacked_state
from consensusml_tpu.utils.tree import consensus_mean

pytestmark = pytest.mark.serving


def _tiny_gpt2():
    from consensusml_tpu.models.gpt2 import GPT2Config, GPT2LM

    return GPT2LM(
        config=GPT2Config(
            vocab_size=64, hidden=32, layers=2, heads=2, max_len=32, dropout=0.0
        )
    )


def _init(model, seq=8, seed=0):
    return model.init(jax.random.key(seed), jnp.zeros((1, seq), jnp.int32))["params"]


# ---------------------------------------------------------------------------
# KV-cache decode correctness
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("family", ["gpt2", "llama"])
def test_incremental_decode_matches_full_forward(family):
    """Token-by-token decode through the slot cache reproduces the full
    causal forward (cache write + length-masked read are exact)."""
    if family == "gpt2":
        model, vocab = _tiny_gpt2(), 64
    else:
        from consensusml_tpu.models.llama import llama_tiny

        model, vocab = llama_tiny(), 256
    B, S, T = 3, 7, 12
    ids = jax.random.randint(jax.random.key(1), (B, S), 0, vocab)
    params = _init(model, seq=S)
    full = np.asarray(model.apply({"params": params}, ids, deterministic=True))

    cfg = model.config
    kvh = getattr(cfg, "kv_heads", cfg.heads)
    d = getattr(cfg, "head_dim", cfg.hidden // cfg.heads)
    cache = [
        {
            "k": jnp.zeros((B, T, kvh, d), cfg.dtype),
            "v": jnp.zeros((B, T, kvh, d), cfg.dtype),
        }
        for _ in range(cfg.layers)
    ]
    out = []
    for t in range(S):
        pos = jnp.full((B,), t, jnp.int32)
        logits, cache = model.apply(
            {"params": params}, ids[:, t : t + 1], deterministic=True,
            positions=pos, kv_cache=cache,
        )
        out.append(np.asarray(logits[:, 0]))
    np.testing.assert_allclose(np.stack(out, axis=1), full, atol=1e-4, rtol=1e-4)


def test_prefill_return_kv_is_logit_neutral():
    """return_kv must not perturb the training/eval forward."""
    model = _tiny_gpt2()
    params = _init(model)
    ids = jax.random.randint(jax.random.key(2), (2, 8), 0, 64)
    plain = model.apply({"params": params}, ids, deterministic=True)
    with_kv, kvs = model.apply(
        {"params": params}, ids, deterministic=True, return_kv=True
    )
    assert np.array_equal(np.asarray(plain), np.asarray(with_kv))
    assert len(kvs) == model.config.layers
    assert kvs[0][0].shape == (2, 8, 2, 16)  # (B, S, H, D)


def test_remat_model_still_serves():
    """remat is a backward-pass lever; the serving forwards (return_kv /
    kv_cache) bypass it rather than pushing python bools through
    nn.remat's tracer boundary."""
    from consensusml_tpu.models.gpt2 import GPT2Config, GPT2LM

    model = GPT2LM(
        config=GPT2Config(
            vocab_size=64, hidden=32, layers=2, heads=2, max_len=32,
            dropout=0.0, remat=True,
        )
    )
    params = _init(model)
    ids = jax.random.randint(jax.random.key(4), (1, 8), 0, 64)
    logits, kvs = model.apply(
        {"params": params}, ids, deterministic=True, return_kv=True
    )
    assert len(kvs) == 2
    plain = model.apply({"params": params}, ids, deterministic=True)
    assert np.array_equal(np.asarray(plain), np.asarray(logits))


@pytest.mark.filterwarnings(
    # the engine thread re-raises ON PURPOSE (loud death in logs beats a
    # mystery hang); pytest surfaces that as this warning
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
def test_engine_death_fails_handles_loudly():
    """A device error mid-serving must terminate handles (cancelled) and
    turn later submits into a clear 'engine died' error — never a silent
    hang."""
    model = _tiny_gpt2()
    engine = Engine(model, _init(model), ServeConfig(num_slots=2, max_len=32))
    boom = RuntimeError("simulated device OOM")

    def dying_prefill(*a, **k):
        raise boom

    engine._prefill_fn = dying_prefill
    h = engine.submit([1, 2, 3])
    r = h.result(timeout=30)  # not a hang
    assert r.finish_reason == "cancelled"
    engine._thread.join(timeout=10)
    with pytest.raises(RuntimeError, match="engine died on RuntimeError"):
        engine.submit([4, 5])


def test_prefill_buckets_cover_and_cap():
    assert prefill_buckets(32) == (8, 16, 32)
    assert prefill_buckets(24) == (8, 16, 24)
    assert prefill_buckets(8) == (8,)


# ---------------------------------------------------------------------------
# export artifact
# ---------------------------------------------------------------------------


def test_export_roundtrip_and_meta(tmp_path):
    bundle = configs.build("gpt2_topk", "smoke")
    state = init_stacked_state(
        bundle.cfg, bundle.init_params, jax.random.key(0), bundle.world_size
    )
    path = export_serving(
        str(tmp_path / "art"), state, config_name="gpt2_topk", scale="smoke"
    )
    meta = serving_meta(path)
    assert meta["config_name"] == "gpt2_topk"
    assert meta["scale"] == "smoke"
    assert meta["world_size"] == bundle.world_size
    assert meta["round"] == 0
    _meta, params, model_state = load_serving(path)
    want = jax.device_get(consensus_mean(state.params))
    got_leaves = jax.tree.leaves(params)
    want_leaves = jax.tree.leaves(want)
    assert len(got_leaves) == len(want_leaves)
    for g, w in zip(got_leaves, want_leaves):
        assert np.array_equal(np.asarray(g), np.asarray(w))
    assert model_state == {}


def test_load_serving_rejects_non_artifact(tmp_path):
    with pytest.raises(ValueError, match="not a serving artifact"):
        serving_meta(str(tmp_path))


def test_engine_rejects_non_lm_model():
    from consensusml_tpu.models import MLP

    with pytest.raises(ValueError, match="no KV-cache decode path"):
        Engine(MLP(hidden=8), {})


# ---------------------------------------------------------------------------
# golden parity: export→serve (prefill-only) == evaluate's mean path
# ---------------------------------------------------------------------------


def test_golden_parity_export_serve_vs_evaluate_mean(tmp_path):
    """The deployed model IS the evaluated model: logits served through
    the engine's prefill-only scoring path match the consensus-mean eval
    path bit for bit on the same batch."""
    bundle = configs.build("gpt2_topk", "smoke")
    state = init_stacked_state(
        bundle.cfg, bundle.init_params, jax.random.key(3), bundle.world_size
    )
    batch = next(iter(bundle.eval_batches(1, 0)))
    ids = batch["input_ids"]

    # the eval path, exactly as make_stacked_eval_step computes the mean
    # model: shared consensus_mean INSIDE jit over the stacked params
    model = bundle.model
    eval_logits = jax.jit(
        lambda p, i: model.apply(
            {"params": consensus_mean(p)}, i, deterministic=True
        )
    )(state.params, ids)

    path = export_serving(
        str(tmp_path / "art"), state, config_name="gpt2_topk", scale="smoke"
    )
    engine = load_engine(path, ServeConfig(num_slots=2))
    try:
        served = engine.score(ids)
        assert np.array_equal(np.asarray(served), np.asarray(eval_logits)), (
            "export→serve logits drifted from the evaluate mean path"
        )
    finally:
        engine.shutdown(drain=False)


# ---------------------------------------------------------------------------
# continuous batcher / engine behavior
# ---------------------------------------------------------------------------


def test_submit_validation_and_drain_rejection():
    model = _tiny_gpt2()
    engine = Engine(model, _init(model), ServeConfig(num_slots=2, max_len=32))
    try:
        with pytest.raises(ValueError, match="empty prompt"):
            engine.submit([])
        with pytest.raises(ValueError, match="max_new_tokens"):
            engine.submit([1], max_new_tokens=0)
        with pytest.raises(ValueError, match="exceeds"):
            engine.submit(list(range(30)), max_new_tokens=10)
    finally:
        engine.shutdown()
    with pytest.raises(RuntimeError, match="draining"):
        engine.submit([1, 2])


def test_bounded_queue_rejects_when_full():
    model = _tiny_gpt2()
    # one slot + depth-1 queue, long generations: the flood must hit Full
    engine = Engine(
        model, _init(model),
        ServeConfig(num_slots=1, max_len=32, queue_depth=1, max_new_tokens=24),
    )
    try:
        with pytest.raises(queue.Full):
            for _ in range(20):
                engine.submit([1, 2, 3], block=False)
    finally:
        engine.shutdown(drain=False)


def test_engine_serves_8_concurrent_streams_zero_recompiles():
    """≥8 concurrent streams, mixed prompt lengths spanning every prefill
    bucket, submitted from client threads — all complete via the
    continuous batcher and the compiled-program set never grows after
    warmup."""
    model = _tiny_gpt2()
    engine = Engine(
        model, _init(model),
        ServeConfig(num_slots=8, max_len=32, max_new_tokens=6),
    )
    try:
        warm = engine.warmup()
        assert warm["prefill"] == len(engine.buckets) and warm["decode"] == 1
        rng = np.random.default_rng(0)
        lens = [2, 3, 7, 8, 9, 15, 16, 17, 20, 25, 5, 11]  # every bucket
        handles: list = [None] * len(lens)

        def client(i):
            ids = rng.integers(0, 63, size=lens[i]).tolist()
            handles[i] = engine.submit(ids, max_new_tokens=6)

        threads = [threading.Thread(target=client, args=(i,)) for i in range(len(lens))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        results = [h.result(timeout=60) for h in handles]
        assert all(r.finish_reason == "max_tokens" for r in results)
        assert all(len(r.tokens) == 6 for r in results)
        stats = engine.stats()
        assert stats["mean_batch_occupancy"] > 0.25  # actually batched
        after = engine.compile_counts()
        assert after["prefill"] == warm["prefill"], "prefill recompiled"
        assert after["decode"] == warm["decode"], "decode recompiled"
    finally:
        engine.shutdown()


def test_decode_is_deterministic_across_batching():
    """A request's tokens must not depend on what shares the batch:
    serve the same prompt alone and alongside 7 others."""
    model = _tiny_gpt2()
    params = _init(model)
    prompt = [5, 9, 2, 40, 11]

    def serve_once(extra):
        engine = Engine(
            model, params, ServeConfig(num_slots=8, max_len=32, max_new_tokens=8)
        )
        try:
            others = [
                engine.submit([int(x) for x in np.random.default_rng(i).integers(0, 63, size=4 + i)])
                for i in range(extra)
            ]
            h = engine.submit(prompt)
            out = h.result(timeout=60).tokens
            for o in others:
                o.result(timeout=60)
            return out
        finally:
            engine.shutdown()

    assert serve_once(0) == serve_once(7)


# ---------------------------------------------------------------------------
# socket front-end
# ---------------------------------------------------------------------------


def test_socket_server_streams_and_drains():
    import sys, os

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from tools.loadgen import _socket_submit

    model = _tiny_gpt2()
    engine = Engine(
        model, _init(model), ServeConfig(num_slots=4, max_len=32, max_new_tokens=4)
    )
    server = ServeServer(engine)
    host, port = server.address
    submit = _socket_submit(host, port)
    rs = [submit([1, 2, 3, 4], 4) for _ in range(3)]
    assert all(len(r["tokens"]) == 4 for r in rs)
    assert all(r["ttft_s"] > 0 for r in rs)
    server.shutdown(drain=True)  # graceful: everything admitted completed
    with pytest.raises(Exception):  # listener is gone
        submit([1, 2], 2)


# ---------------------------------------------------------------------------
# the end-to-end CPU demo: train → --export-serving → serve
# ---------------------------------------------------------------------------


def test_e2e_train_export_serve_demo(tmp_path):
    """Tier-1 acceptance demo: a real (tiny) training run hands off to
    serving through the CLI flag; the engine then serves 8+ concurrent
    mixed-length streams with jaxpr-asserted zero decode recompiles."""
    import train as train_cli

    art = str(tmp_path / "serving")
    rc = train_cli.main(
        [
            "--config", "gpt2_topk", "--device", "cpu", "--backend", "simulated",
            "--workers", "2", "--rounds", "2", "--log-every", "1",
            "--export-serving", art,
        ]
    )
    assert rc == 0
    meta = serving_meta(art)
    assert meta == {
        "config_name": "gpt2_topk", "scale": "smoke", "round": 2, "world_size": 2,
        "generation": 1,  # first export at this path (hot-swap ordering key)
    }

    # jaxpr-asserted zero recompiles: the decode contract (step r's output
    # cache fed back traces byte-identically) holds for the served config
    from consensusml_tpu.analysis import jaxpr_contracts as jc

    bundle = configs.build("gpt2_topk", "smoke")
    assert jc._check_decode_jaxpr("gpt2_topk", bundle) == []

    engine = load_engine(art, ServeConfig(num_slots=8, max_len=32, max_new_tokens=5))
    try:
        warm = engine.warmup()
        rng = np.random.default_rng(1)
        handles = [
            engine.submit(rng.integers(0, 63, size=n).tolist())
            for n in (2, 4, 6, 8, 10, 14, 16, 18, 22, 26)  # mixed buckets
        ]
        results = [h.result(timeout=120) for h in handles]
        assert len(results) >= 8
        assert all(len(r.tokens) == 5 for r in results)
        after = engine.compile_counts()
        assert (after["prefill"], after["decode"]) == (
            warm["prefill"], warm["decode"],
        ), "serving recompiled after warmup"
        assert engine.stats()["mean_batch_occupancy"] > 0.2
    finally:
        engine.shutdown()
