"""Tracing/profiling subsystem: timer stats, fences, xprof trace dump,
and the --profile-dir CLI path."""

import glob
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from consensusml_tpu.utils import RoundTimer, annotate, fence, trace

pytestmark = pytest.mark.profiling


def test_round_timer_separates_warmup_and_steady_state():
    timer = RoundTimer(warmup=1)
    for i in range(4):
        with timer.lap():
            time.sleep(0.05 if i == 0 else 0.01)
    stats = timer.stats()
    assert stats.count == 3  # warmup lap excluded
    assert 0.005 < stats.p50_s < 0.05
    assert stats.max_s < 0.05  # the slow compile lap is not in steady state
    assert "p95" in stats.format()


def test_round_timer_fences_on_metrics():
    @jax.jit
    def slow(x):
        return jnp.sum(x * x)

    timer = RoundTimer(warmup=0)
    metrics = {}
    x = jnp.ones((256, 256))
    with timer.lap(metrics_fn=lambda: metrics):
        metrics = {"loss": slow(x)}
    assert timer.stats().count == 1
    assert np.isfinite(timer.stats().mean_s)


def test_fence_handles_trees_and_empty():
    fence({})
    fence({"a": jnp.ones((3,)), "b": [jnp.zeros(())]})


def test_annotate_composes_with_jit():
    @jax.jit
    def f(x):
        with annotate("gossip"):
            return x * 2

    np.testing.assert_allclose(np.asarray(f(jnp.ones(4))), 2.0)


def test_trace_writes_xprof_dump(tmp_path):
    d = str(tmp_path / "prof")
    with trace(d):
        jnp.sum(jnp.ones((64, 64))).block_until_ready()
    dumped = glob.glob(os.path.join(d, "**", "*"), recursive=True)
    assert dumped, "trace produced no files"


def test_cli_profile_dir(tmp_path):
    from train import main

    d = str(tmp_path / "prof")
    rc = main([
        "--config", "mnist_mlp", "--device", "cpu", "--backend", "simulated",
        "--rounds", "6", "--profile-dir", d, "--log-every", "100",
    ])
    assert rc == 0
    assert glob.glob(os.path.join(d, "**", "*"), recursive=True)
