// Deterministic counter-keyed RNG for the native data pipeline.
//
// Reference parity: the reference's native data-loader layer (SURVEY.md L0
// native components; reference mount empty — see SURVEY.md blocker). Every
// sample's bytes are a pure function of (seed, global sample id), so the
// pipeline is reproducible regardless of thread count or scheduling — the
// property the tests pin down.
#pragma once

#include <cmath>
#include <cstdint>

namespace cml {

static inline uint64_t splitmix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ULL;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
  return x ^ (x >> 31);
}

// xorshift128+ seeded via splitmix64 (never all-zero state).
struct Rng {
  uint64_t s0, s1;

  explicit Rng(uint64_t seed) {
    s0 = splitmix64(seed);
    s1 = splitmix64(s0 ^ 0x6A09E667F3BCC909ULL);
    if (s0 == 0 && s1 == 0) s1 = 1;
  }

  inline uint64_t next() {
    uint64_t x = s0;
    const uint64_t y = s1;
    s0 = y;
    x ^= x << 23;
    s1 = x ^ y ^ (x >> 17) ^ (y >> 26);
    return s1 + y;
  }

  // uniform in [0, 1) with 24 bits of mantissa entropy
  inline float uniform() { return (float)(next() >> 40) * (1.0f / 16777216.0f); }

  inline uint32_t randint(uint32_t n) { return (uint32_t)(next() % n); }

  // full-width variant: spans over 2^32 (huge per-worker token regions)
  // must not truncate — (uint32_t)span would silently bias coverage or,
  // on exact wrap to 0, divide by zero
  inline uint64_t randint64(uint64_t n) { return next() % n; }

  // standard normal via Box-Muller (cosine branch)
  inline float gauss() {
    float u1 = uniform();
    const float u2 = uniform();
    if (u1 < 1e-7f) u1 = 1e-7f;
    return sqrtf(-2.0f * logf(u1)) * cosf(6.28318530717958647692f * u2);
  }
};

}  // namespace cml
