// Threaded prefetching data pipeline: a ring of pre-allocated host slots
// filled by producer threads, consumed in sequence order by Python.
//
// Reference parity: the reference framework's native data-loader /
// prefetcher (SURVEY.md L0 "native components the TPU build must
// re-implement"; reference mount empty, so this is the standard
// producer-consumer ring design, not a translation). TPU fit: the consumer
// overlaps host-side batch synthesis with device compute — while the TPU
// runs round r, threads are already filling rounds r+1..r+depth-1.
//
// Determinism: slot contents are a pure function of (seed, sequence
// number) — producer threads claim sequence numbers atomically but the
// bytes they write never depend on which thread ran. Consumers always
// receive slots in sequence order.
//
// Slot layout: [samples_per_slot * sample_floats] f32, then
//              [samples_per_slot * sample_ints] i32.
//
// Generation kinds:
//   0 = classification: label ~ U(nclasses); image = prototypes[label]
//       + noise * N(0,1)   (prototype table supplied by Python)
//   1 = Markov LM: token chain over a [vocab, 4] successor table; emitted
//       states are in [0, vocab-1) so vocab-1 can serve as [MASK].
//   2 = file classification: sample idx ~ U(worker shard) of a caller-
//       owned (n_items, sample_floats) image table + (n_items,) labels;
//       worker shards are contiguous n_items/world blocks (same layout
//       as data.files.FileClassification.worker_shard). Pointers are
//       BORROWED — the caller keeps the arrays alive.
//   3 = file LM: sample_ints-token windows from a caller-owned flat
//       (n_items,) token stream, each worker drawing starts from its
//       contiguous n_items/world region (data.files.TokenFileDataset).
//
// Kinds 2/3 move the gather/copy work of file-backed datasets onto the
// producer threads, so --data-dir training overlaps host batch assembly
// with device compute exactly like the procedural kinds.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

#include "rng.h"

namespace cml {

enum class SlotState : int { kFree = 0, kFilling = 1, kReady = 2, kInUse = 3 };

struct Slot {
  std::vector<float> floats;
  std::vector<uint8_t> u8;  // used instead of floats when float_bytes == 1
  std::vector<int32_t> ints;
  SlotState state = SlotState::kFree;
  uint64_t seq = 0;  // valid when kReady/kInUse
};

class Loader {
 public:
  Loader(int depth, int nthreads, uint64_t seed, int kind,
         int64_t samples_per_slot, int64_t sample_floats, int64_t sample_ints,
         int32_t nclasses_or_vocab, float noise, const float* prototypes,
         const int32_t* successors, int32_t world = 1,
         const float* file_data = nullptr, const int32_t* file_labels = nullptr,
         const int32_t* file_tokens = nullptr, int64_t n_items = 0,
         int32_t token_bytes = 4, uint64_t start_seq = 0,
         int32_t float_bytes = 4, float qscale = 1.0f, float qoff = 0.0f)
      : depth_(depth),
        seed_(seed),
        kind_(kind),
        samples_per_slot_(samples_per_slot),
        sample_floats_(sample_floats),
        sample_ints_(sample_ints),
        nclasses_(nclasses_or_vocab),
        noise_(noise),
        world_(world),
        file_data_(file_data),
        file_labels_(file_labels),
        file_tokens_(file_tokens),
        n_items_(n_items),
        token_bytes_(token_bytes),
        float_bytes_(float_bytes),
        qscale_(qscale),
        qoff_(qoff) {
    // resume: slot contents are f(seed, seq), so starting both counters at
    // start_seq reproduces the stream from that round in O(1)
    next_produce_ = start_seq;
    next_consume_ = start_seq;
    if (prototypes != nullptr && kind == 0) {
      prototypes_.assign(prototypes,
                         prototypes + (int64_t)nclasses_ * sample_floats_);
    }
    if (successors != nullptr && kind == 1) {
      successors_.assign(successors, successors + (int64_t)nclasses_ * 4);
    }
    slots_.resize(depth_);
    for (auto& s : slots_) {
      if (float_bytes_ == 1) {
        s.u8.resize(samples_per_slot_ * sample_floats_);
      } else {
        s.floats.resize(samples_per_slot_ * sample_floats_);
      }
      s.ints.resize(samples_per_slot_ * sample_ints_);
    }
    for (int t = 0; t < nthreads; ++t) {
      threads_.emplace_back([this] { ProducerLoop(); });
    }
  }

  ~Loader() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_producer_.notify_all();
    cv_consumer_.notify_all();
    for (auto& t : threads_) t.join();
  }

  // Blocks until an in-order slot is ready; returns its index and exposes
  // its buffers. Returns -1 only after destruction begins. Each caller
  // claims its sequence number before waiting, so concurrent consumers
  // wait on distinct slots instead of racing for (and possibly deadlocking
  // on) the same one.
  int Acquire(float** fptr, int32_t** iptr) {
    uint8_t* unused = nullptr;
    return AcquireImpl(fptr, &unused, iptr);
  }

  int AcquireU8(uint8_t** bptr, int32_t** iptr) {
    float* unused = nullptr;
    return AcquireImpl(&unused, bptr, iptr);
  }

  void Release(int idx) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      slots_[idx].state = SlotState::kFree;
    }
    cv_producer_.notify_all();
  }

  uint64_t Produced() {
    std::lock_guard<std::mutex> lk(mu_);
    return next_produce_;
  }

  int32_t FloatBytes() const { return float_bytes_; }

 private:
  int AcquireImpl(float** fptr, uint8_t** bptr, int32_t** iptr) {
    std::unique_lock<std::mutex> lk(mu_);
    const uint64_t want = next_consume_++;
    Slot& slot = slots_[want % depth_];
    cv_consumer_.wait(lk, [&] {
      return stop_ || (slot.state == SlotState::kReady && slot.seq == want);
    });
    if (stop_) return -1;
    slot.state = SlotState::kInUse;
    *fptr = slot.floats.data();
    *bptr = slot.u8.data();
    *iptr = slot.ints.data();
    return (int)(want % depth_);
  }

  void ProducerLoop() {
    for (;;) {
      uint64_t seq;
      Slot* slot;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_producer_.wait(lk, [&] {
          if (stop_) return true;
          // the slot for the next unclaimed seq must be free
          return slots_[next_produce_ % depth_].state == SlotState::kFree;
        });
        if (stop_) return;
        seq = next_produce_++;
        slot = &slots_[seq % depth_];
        slot->state = SlotState::kFilling;
      }
      Fill(*slot, seq);
      {
        std::lock_guard<std::mutex> lk(mu_);
        slot->state = SlotState::kReady;
        slot->seq = seq;
      }
      cv_consumer_.notify_all();
      cv_producer_.notify_all();
    }
  }

  void Fill(Slot& slot, uint64_t seq) {
    for (int64_t i = 0; i < samples_per_slot_; ++i) {
      const uint64_t gid = seq * (uint64_t)samples_per_slot_ + (uint64_t)i;
      Rng rng(splitmix64(seed_ ^ (gid * 0x9E3779B97F4A7C15ULL + 0x5DEECE66DULL)));
      if (kind_ == 2 || kind_ == 3) {
        // worker of this sample: contiguous per-worker sample blocks
        const int64_t per_slot = samples_per_slot_ / world_;
        const int64_t w = (per_slot > 0) ? (i / per_slot) : 0;
        if (kind_ == 2) {
          const int64_t shard = n_items_ / world_;
          const int64_t idx =
              w * shard + (int64_t)rng.randint64((uint64_t)shard);
          const float* src = file_data_ + idx * sample_floats_;
          if (float_bytes_ == 1) {
            // u8 wire: producer threads do the quantize pass so the
            // consumer ships 1/4 the bytes and dequants on device
            uint8_t* dst = slot.u8.data() + i * sample_floats_;
            for (int64_t j = 0; j < sample_floats_; ++j) {
              dst[j] = QuantU8(src[j]);
            }
          } else {
            std::memcpy(slot.floats.data() + i * sample_floats_, src,
                        sizeof(float) * sample_floats_);
          }
          for (int64_t j = 0; j < sample_ints_; ++j) {
            slot.ints[i * sample_ints_ + j] = file_labels_[idx];
          }
        } else {
          const int64_t region = n_items_ / world_;
          const int64_t span = region - sample_ints_;  // validated at create
          const int64_t start =
              w * region + (int64_t)rng.randint64((uint64_t)span);
          int32_t* dst = slot.ints.data() + i * sample_ints_;
          if (token_bytes_ == 2) {
            // widen uint16 ids on the fly: lets Python hand us the raw
            // memmap without materializing an int32 copy of the corpus
            const uint16_t* src =
                reinterpret_cast<const uint16_t*>(file_tokens_) + start;
            for (int64_t t = 0; t < sample_ints_; ++t) dst[t] = (int32_t)src[t];
          } else {
            std::memcpy(dst, file_tokens_ + start,
                        sizeof(int32_t) * sample_ints_);
          }
        }
        continue;
      }
      if (kind_ == 0) {
        const int32_t label = (int32_t)rng.randint((uint32_t)nclasses_);
        const float* proto =
            prototypes_.empty() ? nullptr
                                : prototypes_.data() + (int64_t)label * sample_floats_;
        if (float_bytes_ == 1) {
          uint8_t* img = slot.u8.data() + i * sample_floats_;
          for (int64_t j = 0; j < sample_floats_; ++j) {
            const float v =
                (proto != nullptr ? proto[j] : 0.0f) + noise_ * rng.gauss();
            img[j] = QuantU8(v);
          }
        } else {
          float* img = slot.floats.data() + i * sample_floats_;
          for (int64_t j = 0; j < sample_floats_; ++j) {
            img[j] = (proto != nullptr ? proto[j] : 0.0f) + noise_ * rng.gauss();
          }
        }
        for (int64_t j = 0; j < sample_ints_; ++j) {
          slot.ints[i * sample_ints_ + j] = label;
        }
      } else {  // Markov LM
        int32_t state = (int32_t)rng.randint((uint32_t)(nclasses_ - 1));
        int32_t* toks = slot.ints.data() + i * sample_ints_;
        for (int64_t t = 0; t < sample_ints_; ++t) {
          toks[t] = state;
          state = successors_[(int64_t)state * 4 + rng.randint(4)];
        }
      }
    }
  }

  const int depth_;
  const uint64_t seed_;
  const int kind_;
  const int64_t samples_per_slot_;
  const int64_t sample_floats_;
  const int64_t sample_ints_;
  const int32_t nclasses_;
  const float noise_;
  const int32_t world_;
  const float* file_data_;      // borrowed (kind 2)
  const int32_t* file_labels_;  // borrowed (kind 2)
  const int32_t* file_tokens_;  // borrowed (kind 3; raw uint16 when token_bytes_==2)
  const int64_t n_items_;
  const int32_t token_bytes_;  // 2 (uint16 memmap passthrough) or 4 (int32)
  const int32_t float_bytes_;  // 4 (f32 wire) or 1 (u8 wire)
  const float qscale_;  // u8 = clip((x + qoff) * qscale); x^ = u8/qscale - qoff
  const float qoff_;

  uint8_t QuantU8(float v) const {
    float q = (v + qoff_) * qscale_;
    if (q < 0.0f) q = 0.0f;
    if (q > 255.0f) q = 255.0f;
    return (uint8_t)(q + 0.5f);
  }
  std::vector<float> prototypes_;
  std::vector<int32_t> successors_;

  std::mutex mu_;
  std::condition_variable cv_producer_;
  std::condition_variable cv_consumer_;
  std::vector<Slot> slots_;
  std::vector<std::thread> threads_;
  uint64_t next_produce_ = 0;
  uint64_t next_consume_ = 0;
  bool stop_ = false;
};

}  // namespace cml

extern "C" {

void* cml_loader_create(int depth, int nthreads, uint64_t seed, int kind,
                        int64_t samples_per_slot, int64_t sample_floats,
                        int64_t sample_ints, int32_t nclasses_or_vocab,
                        float noise, const float* prototypes,
                        const int32_t* successors, uint64_t start_seq,
                        int32_t float_bytes, float qscale, float qoff) {
  if (depth < 1 || nthreads < 1 || samples_per_slot < 1) return nullptr;
  if (kind != 0 && kind != 1) return nullptr;
  if (kind == 1 && (successors == nullptr || nclasses_or_vocab < 2)) return nullptr;
  if (nclasses_or_vocab < 1) return nullptr;
  if (float_bytes != 4 && float_bytes != 1) return nullptr;
  // u8 wire quantizes the FLOAT payload; only the classification kind (0)
  // has one — mirrors the cml_loader_create_file guard (kind 2 only)
  if (float_bytes == 1 && (kind != 0 || qscale <= 0.0f)) return nullptr;
  return new cml::Loader(depth, nthreads, seed, kind, samples_per_slot,
                         sample_floats, sample_ints, nclasses_or_vocab, noise,
                         prototypes, successors, /*world=*/1, nullptr, nullptr,
                         nullptr, 0, 4, start_seq, float_bytes, qscale, qoff);
}

// File-backed kinds (2 = classification table, 3 = token windows). The
// data/labels/tokens buffers are BORROWED for the loader's lifetime.
void* cml_loader_create_file(int depth, int nthreads, uint64_t seed, int kind,
                             int64_t samples_per_slot, int64_t sample_floats,
                             int64_t sample_ints, int32_t world,
                             const float* data, const int32_t* labels,
                             const int32_t* tokens, int64_t n_items,
                             int32_t token_bytes, uint64_t start_seq,
                             int32_t float_bytes, float qscale, float qoff) {
  if (depth < 1 || nthreads < 1 || samples_per_slot < 1) return nullptr;
  if (world < 1 || samples_per_slot % world != 0) return nullptr;
  if (n_items < world) return nullptr;
  if (token_bytes != 2 && token_bytes != 4) return nullptr;
  if (float_bytes != 4 && float_bytes != 1) return nullptr;
  if (float_bytes == 1 && (kind != 2 || qscale <= 0.0f)) return nullptr;
  if (kind == 2) {
    if (data == nullptr || labels == nullptr || sample_floats < 1) return nullptr;
    if (n_items / world < 1) return nullptr;
  } else if (kind == 3) {
    if (tokens == nullptr || sample_ints < 1) return nullptr;
    if (n_items / world <= sample_ints) return nullptr;  // span must be > 0
  } else {
    return nullptr;
  }
  return new cml::Loader(depth, nthreads, seed, kind, samples_per_slot,
                         sample_floats, sample_ints, /*nclasses=*/1,
                         /*noise=*/0.0f, nullptr, nullptr, world, data, labels,
                         tokens, n_items, token_bytes, start_seq, float_bytes,
                         qscale, qoff);
}

int cml_loader_acquire(void* h, float** fptr, int32_t** iptr) {
  return static_cast<cml::Loader*>(h)->Acquire(fptr, iptr);
}

int cml_loader_acquire_u8(void* h, uint8_t** bptr, int32_t** iptr) {
  return static_cast<cml::Loader*>(h)->AcquireU8(bptr, iptr);
}

int32_t cml_loader_float_bytes(void* h) {
  return static_cast<cml::Loader*>(h)->FloatBytes();
}

void cml_loader_release(void* h, int idx) {
  static_cast<cml::Loader*>(h)->Release(idx);
}

uint64_t cml_loader_produced(void* h) {
  return static_cast<cml::Loader*>(h)->Produced();
}

void cml_loader_destroy(void* h) { delete static_cast<cml::Loader*>(h); }

}  // extern "C"
