// CPU compression kernels: per-chunk int8 quantization and magnitude
// top-k selection.
//
// Reference parity: the reference's CUDA gradient-compression / top-k
// sparsification kernels (BASELINE.json north_star; SURVEY.md L0 — mount
// empty). On TPU the hot path is the Pallas implementation
// (consensusml_tpu/compress/kernels.py); these native kernels are the
// HOST-side leg — an independent third implementation used for
// cross-checking the jnp/Pallas semantics and for host-side work
// (checkpoint compression, DCN payload prep) where no accelerator is in
// the loop.
//
// Numerical semantics are pinned to consensusml_tpu/compress/reference.py:
//   quant:  scale = absmax/127; q = clip(round_nearest_even(x/scale));
//           zero chunks -> scale 0, decode to exact zeros.
//   top-k:  k largest by |x|, descending, ties broken by lower index
//           (jax.lax.top_k ordering).

#include <algorithm>
#include <cfenv>
#include <cmath>
#include <cstdint>
#include <numeric>
#include <thread>
#include <vector>

namespace cml {

// Run fn(chunk_index) over [0, nchunks) on up to hardware_concurrency threads.
template <typename Fn>
static void ParallelFor(int64_t nchunks, Fn fn) {
  const int64_t hw = (int64_t)std::thread::hardware_concurrency();
  const int64_t nthreads = std::max<int64_t>(1, std::min<int64_t>(hw, nchunks));
  if (nthreads == 1) {
    for (int64_t c = 0; c < nchunks; ++c) fn(c);
    return;
  }
  std::vector<std::thread> pool;
  pool.reserve(nthreads);
  const int64_t per = (nchunks + nthreads - 1) / nthreads;
  for (int64_t t = 0; t < nthreads; ++t) {
    const int64_t lo = t * per;
    const int64_t hi = std::min(nchunks, lo + per);
    if (lo >= hi) break;
    pool.emplace_back([lo, hi, &fn] {
      for (int64_t c = lo; c < hi; ++c) fn(c);
    });
  }
  for (auto& th : pool) th.join();
}

}  // namespace cml

extern "C" {

// q: [nchunks, chunk] int8, scales: [nchunks] f32
void cml_quant_int8(const float* x, int64_t nchunks, int64_t chunk, int8_t* q,
                    float* scales) {
  cml::ParallelFor(nchunks, [&](int64_t c) {
    const float* row = x + c * chunk;
    int8_t* qrow = q + c * chunk;
    float absmax = 0.0f;
    for (int64_t j = 0; j < chunk; ++j) absmax = std::max(absmax, std::fabs(row[j]));
    const float scale = absmax / 127.0f;
    scales[c] = scale;
    const float inv = scale > 0.0f ? 1.0f / scale : 0.0f;
    for (int64_t j = 0; j < chunk; ++j) {
      // nearbyintf under the default FP environment = round-to-nearest-even,
      // matching jnp.rint
      float r = std::nearbyintf(row[j] * inv);
      r = std::min(127.0f, std::max(-127.0f, r));
      qrow[j] = (int8_t)r;
    }
  });
}

void cml_dequant_int8(const int8_t* q, const float* scales, int64_t nchunks,
                      int64_t chunk, float* out) {
  cml::ParallelFor(nchunks, [&](int64_t c) {
    const float scale = scales[c];
    const int8_t* qrow = q + c * chunk;
    float* row = out + c * chunk;
    for (int64_t j = 0; j < chunk; ++j) row[j] = (float)qrow[j] * scale;
  });
}

// vals/idx: [k]; k largest by |x|, descending magnitude, ties -> lower index.
void cml_topk(const float* x, int64_t n, int64_t k, float* vals, int32_t* idx) {
  if (k > n) k = n;
  std::vector<int32_t> order(n);
  std::iota(order.begin(), order.end(), 0);
  const auto cmp = [x](int32_t a, int32_t b) {
    const float fa = std::fabs(x[a]), fb = std::fabs(x[b]);
    if (fa != fb) return fa > fb;
    return a < b;
  };
  std::partial_sort(order.begin(), order.begin() + k, order.end(), cmp);
  for (int64_t i = 0; i < k; ++i) {
    idx[i] = order[i];
    vals[i] = x[order[i]];
  }
}

// Per-chunk top-k: vals/idx are [nchunks, k]; indices are LOCAL to the chunk.
void cml_topk_chunks(const float* x, int64_t nchunks, int64_t chunk, int64_t k,
                     float* vals, int32_t* idx) {
  cml::ParallelFor(nchunks, [&](int64_t c) {
    cml_topk(x + c * chunk, chunk, k, vals + c * k, idx + c * k);
  });
}

}  // extern "C"
